"""LLM serving engine: slot-based continuous batching with token streaming.

The decode-serving core for BASELINE.json configs 3/5 (gRPC streaming
Gemma decode; multi-chip tensor-parallel serving). No counterpart in the
reference repo — this is the TPU-native replacement for its goroutine-per-
request model at the model-serving layer (SURVEY.md §7 hard part 5:
"continuous batching / slot-based scheduler is the real design problem").

Design (all shapes static; a bounded set of compiled executables):

- **Slots over a paged block pool (default).** A fixed decode batch of
  S slots whose KV lives in ONE device-resident pool of fixed-size
  blocks [n_layers, NB, block, hkv, hd], read and written through
  per-slot block tables (gofr_tpu.kvcache.paged): blocks materialize as
  each cursor advances, sibling prompts share every common prefix block
  in place (refcounted, copy-on-write), and decode attention goes
  through ops.paged_chunk_decode_attention (Pallas paged kernel on TPU,
  dense-gather fallback elsewhere). TPU_LLM_KV_INT8 stores blocks int8.
  kv_paged=False restores the contiguous layouts — a dense
  [n_layers, S, max_seq_len, hkv, hd] slab for global attention, or a
  window-bounded ROLLING ring for sliding-window models — as the
  token-identical A/B lever. Inactive slots are masked (their tokens
  are discarded on host; their cursors never advance).
- **Prefix reuse.** With prefix_cache_mb > 0, admission consults the
  prefix index — the paged layout's RADIX TREE over token ids (every
  block-aligned shared prefix hits, exact published prompts skip
  prefill entirely via copied tails + stored logits), or the contiguous
  layout's refcounted LRU cache of whole retained rows
  (gofr_tpu.kvcache; hit/miss/partial_hit counters in
  stats()["kvcache"]). With session_mb > 0, X-GoFr-Session
  conversations keep their blocks resident between turns and spill to
  host RAM when cold (docs/advanced-guide/kv-cache.md#sessions).
- **Fused decode chunks.** Decode advances ALL slots K steps per dispatch
  (models.transformer.decode_chunk: a lax.scan over a chunk-ring-buffer
  layer body with on-device sampling — the main cache is read-only inside
  a chunk and merged once at chunk end, so no per-step scatter). One
  host→device dispatch per K tokens amortizes dispatch latency, and the
  engine keeps up to `lookahead` chunks in flight, chaining each chunk's
  input tokens from the previous chunk's on-device output so the device
  never waits for host readback.
- **Chunked prefill under a token budget (default).** Prompts are split
  into fixed-shape prefill chunks (TPU_LLM_PREFILL_CHUNK, default 64;
  the configured prefill_buckets survive only as the available chunk
  compile shapes) that append into the slot's KV cache incrementally via
  a per-request `prefill_pos` cursor — a partial-prefill slot is
  resident but not decoding. Each device step packs up to
  TPU_LLM_STEP_TOKEN_BUDGET (default 256) tokens of pending prefill
  chunks COALESCED with the active slots' decode chunk into one jitted
  unified-step program, so no request ever waits behind more than one
  bounded step (Sarathi-style chunked prefill + piggybacked decode; the
  monolithic path held the chip for admit_cap x bucket tokens per wave
  and starved decode — BENCH_r05's 1.46 SLO p99/p50 was that
  head-of-line wait). A prompt whose PREFIX is already in the prefix
  cache seeds `prefill_pos` mid-prompt and only the unshared chunks run.
  step_token_budget=0 restores the monolithic wave path (the A/B lever
  the equality tests drive).
- **Speculative decoding (opt-in, TPU_LLM_SPEC=1).** A host-side
  n-gram/prompt-lookup drafter (gofr_tpu.spec) proposes up to
  TPU_LLM_SPEC_DRAFT tokens per decoding slot; ONE fused verify program
  (llm.step_v, models.transformer.verify_chunk) scores every draft
  position against the slot KV in a single write-then-attend pass,
  samples each with the regular top-k machinery, accepts the longest
  agreeing prefix ON DEVICE (tail/cursors stay chained; rejected rows
  roll back behind the cursor), and the host emits the accepted span as
  one multi-token push. Greedy spec-on is token-identical to spec-off;
  temperature is distribution-preserving. Verifies pipeline against
  their own optimistic draft stream; when nothing drafts the engine
  falls back to the plain chunk pipeline and periodically re-probes
  (docs/advanced-guide/speculative-decoding.md).
- **Admission without stalling decode.** Monolithic-path prefill waves
  dispatch asynchronously BETWEEN decode chunks; the first sampled token
  is merged into the on-device tail vector by a jitted scatter (no host
  round trip), and prefilled KV rows are copied into free slots via ONE
  jitted insert-many. Decode chunks already in flight keep streaming —
  their tokens for a reused slot are dropped on host via per-slot
  generation tags, never by draining the pipeline (the r2 engine's
  flush-before-admit barrier cost 72% of raw decode throughput).
- **On-device sampling.** Greedy or temperature sampling happens inside the
  chunk; the host syncs one [K, S] int32 array per chunk (started with
  copy_to_host_async at dispatch) instead of logits.
- **Streaming.** Each request owns a thread-safe queue; the engine thread
  pushes per-chunk token LISTS as fetches complete; consumers iterate
  stream() (sync) or astream() (async) and detach by cancelling — a
  detached request just frees its slot, never stalling the batch.
- **Observability.** With a tracer wired, submit() captures the caller's
  trace context (the scheduler/collector threads break contextvar flow)
  and the engine emits an llm.request span with queue_wait / prefill /
  per-chunk decode / emit children; with metrics wired it records the
  app_llm_* phase histograms and engine-state gauges; with a logger it
  emits one JSON wide-event line per completed request. stats()["phases"]
  and debug_state() expose recent-window p50/p99 and the live slot table
  (docs/advanced-guide/observability-serving.md). Every jitted program
  goes through profiling.instrument_jit — per-shape compile wall time,
  cost_analysis FLOPs, and cache-hit counts land in the process compile
  registry (/.well-known/debug/compiles) — and each prefill wave /
  decode chunk feeds analytic-FLOPs MFU, tokens/s/chip, and a roofline
  compute-vs-HBM classification (stats()["mfu"], app_llm_mfu gauges;
  docs/advanced-guide/profiling.md).

Tensor parallelism: pass mesh + param_specs (or TPU_LLM_TP via
register_llm) and the engine serves the model across an ICI submesh —
the KV pool/slab is COMMITTED to parallel.sharding.kv_specs (heads
sharded when the TP degree divides n_kv_heads, replicated under MQA),
and the sharded decode path double-buffers the next layer's weight
all-gather behind the current layer's matmul (TPU_LLM_TP_OVERLAP;
docs/advanced-guide/sharded-serving.md) — identical tokens single-chip
and multi-chip. Disaggregated prefill/decode role pools with
device-to-device KV handoff live in gofr_tpu.llm_disagg.
Quantization: quantize=True serves int8 weights (models.quant), halving
the HBM stream that bounds decode.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

__all__ = [
    "LLMEngine",
    "ReplicatedLLMEngine",
    "GenRequest",
    "EngineOverloaded",
    "EngineStoppedError",
    "EngineDraining",
    "PoisonedRequestError",
]

_EOS_DEFAULT = -1  # no EOS cut by default (random-weight models)

# Serializes app_llm_* registration across engines (ReplicatedLLMEngine
# builds N engines on parallel threads; same rationale as the kvcache
# module's registration lock).
_OBS_REG_LOCK = threading.Lock()


def _register_phase_metrics(metrics) -> None:
    """Engine phase-latency instruments, shared across engines/replicas
    (series are separated by the model label). Histograms reuse
    TPU_BUCKETS (100us..5s) — queue wait, TTFT, and per-token latencies
    all live inside that envelope on every supported config."""
    from .metrics import TPU_BUCKETS

    with _OBS_REG_LOCK:
        for name, desc in (
            ("app_llm_queue_wait_seconds", "llm submit->slot admission wait s"),
            ("app_llm_ttft_seconds", "llm submit->first emitted token s"),
            ("app_llm_time_per_output_token_seconds",
             "llm steady-state decode s/token (requests with >1 token)"),
            ("app_llm_decode_step_seconds",
             "llm decode dispatch->fetch s/step (chunk=len, wave=pow2 active)"),
        ):
            if not metrics.has(name):
                metrics.new_histogram(name, desc, TPU_BUCKETS)
        if not metrics.has("app_llm_step_seconds"):
            # unified-step dispatch->fetch wall time (chunked scheduler)
            metrics.new_histogram(
                "app_llm_step_seconds",
                "llm unified step dispatch->fetch s (prefill chunks + "
                "piggybacked decode)", TPU_BUCKETS,
            )
        # sharded / disaggregated serving (docs/advanced-guide/
        # sharded-serving.md)
        if not metrics.has("app_llm_kv_handoff_seconds"):
            metrics.new_histogram(
                "app_llm_kv_handoff_seconds",
                "llm disaggregated prefill->decode KV handoff wall s "
                "(export + transfer + import)", TPU_BUCKETS,
            )
        if not metrics.has("app_llm_collective_seconds"):
            metrics.new_histogram(
                "app_llm_collective_seconds",
                "llm sharded-serving collective/transfer wall s "
                "(phase=weight_shard|kv_handoff_gather|"
                "kv_handoff_transfer|kv_handoff_scatter)", TPU_BUCKETS,
            )
        if not metrics.has("app_llm_tp_degree"):
            metrics.new_gauge(
                "app_llm_tp_degree",
                "tensor-parallel degree of each engine's submesh "
                "(1 = single-chip)",
            )
        if not metrics.has("app_llm_kv_handoffs_total"):
            metrics.new_counter(
                "app_llm_kv_handoffs_total",
                "llm disaggregated KV handoffs "
                "(outcome=ok|miss|fallback)",
            )
        if not metrics.has("app_llm_step_tokens"):
            metrics.new_histogram(
                "app_llm_step_tokens",
                "llm tokens packed per unified step (prefill chunk tokens "
                "+ decode steps x active slots)",
                (8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0,
                 2048.0, 4096.0, 8192.0),
            )
        # speculative decoding (gofr_tpu.spec;
        # docs/advanced-guide/speculative-decoding.md)
        for name, desc in (
            ("app_llm_spec_proposed_total",
             "llm speculative draft tokens proposed (n-gram drafter; "
             "constrained=0|1 splits grammar-masked lanes)"),
            ("app_llm_spec_accepted_total",
             "llm speculative draft tokens accepted by verification "
             "(constrained=0|1 splits grammar-masked lanes)"),
            ("app_llm_constrained_requests_total",
             "llm grammar-constrained generation requests accepted "
             "(gofr_tpu.structured)"),
        ):
            if not metrics.has(name):
                metrics.new_counter(name, desc)
        if not metrics.has("app_llm_constrained_mask_seconds"):
            metrics.new_histogram(
                "app_llm_constrained_mask_seconds",
                "llm grammar mask preparation wall s per constrained "
                "submit (dedup hit or table pad + device ship)",
                TPU_BUCKETS,
            )
        if not metrics.has("app_llm_constrained_grammars"):
            metrics.new_gauge(
                "app_llm_constrained_grammars",
                "llm resident compiled grammars in the engine's device "
                "transition table (zeroed at engine close)",
            )
        # multi-tenant LoRA adapter serving (gofr_tpu.lora;
        # docs/advanced-guide/multi-tenancy.md)
        for name, desc in (
            ("app_llm_adapter_requests_total",
             "llm requests attributed to a LoRA adapter (adapter label "
             "names the tenant)"),
            ("app_llm_adapter_swaps_total",
             "llm adapter hot-load publishes (staged gid repointed at a "
             "serving name; old gid drains as a zombie)"),
            ("app_llm_adapter_evictions_total",
             "llm idle resident adapters LRU-evicted to make room for a "
             "load (pool full)"),
        ):
            if not metrics.has(name):
                metrics.new_counter(name, desc)
        if not metrics.has("app_llm_adapters_resident"):
            metrics.new_gauge(
                "app_llm_adapters_resident",
                "llm named LoRA adapters resident in the engine's device "
                "tables (zeroed at engine close)",
            )
        if not metrics.has("app_llm_moe_experts"):
            metrics.new_gauge(
                "app_llm_moe_experts",
                "llm experts per MoE layer of the served model (0 = dense)",
            )
        if not metrics.has("app_llm_spec_tokens_per_step"):
            metrics.new_histogram(
                "app_llm_spec_tokens_per_step",
                "llm tokens emitted per slot per speculative verify step "
                "(accepted draft + 1 bonus; 1 = nothing accepted)",
                (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0),
            )
        for name, desc in (
            ("app_llm_slots_in_use", "llm decode slots holding a live request"),
            ("app_llm_queue_depth", "llm requests waiting for a slot"),
            ("app_llm_admission_backlog",
             "llm requests mid-admission (pulled from queue, not yet slotted)"),
            ("app_llm_step_budget_utilization",
             "tokens packed into the last unified step / step token "
             "budget (can exceed 1: decode always rides and a step "
             "always carries at least one chunk)"),
            ("app_llm_mfu",
             "model FLOPs utilization 0..1 per phase (analytic FLOPs / "
             "measured wall / device peak)"),
            ("app_llm_tokens_per_second_per_chip",
             "llm decoded tokens per second per chip (last chunk)"),
            ("app_llm_roofline_ratio",
             "compute_time/memory_time per phase (>1 compute-bound, "
             "<1 HBM-bandwidth-bound)"),
            ("app_llm_spec_accept_rate",
             "llm cumulative speculative draft acceptance rate 0..1 "
             "(accepted/proposed; zeroed at engine close)"),
        ):
            if not metrics.has(name):
                metrics.new_gauge(name, desc)
    from .profiling import register_compile_metrics

    register_compile_metrics(metrics)  # app_jax_* (own registration lock)
    from .resilience import register_resilience_metrics

    register_resilience_metrics(metrics)  # app_llm_*_total + drain gauge
    from .goodput import register_goodput_metrics

    register_goodput_metrics(metrics)  # app_llm_goodput_* + tenant meters


class EngineOverloaded(RuntimeError):
    """Raised by submit() when the admission queue cap is hit OR when the
    predicted queue wait crosses the shed threshold — the SLO-preserving
    alternative to unbounded queueing (map to HTTP 429). Carries
    `status_code` so the responder's statusCodeResponder seam translates
    it without a handler-side catch, and `retry_after` (seconds) so both
    edges tell the client WHEN capacity is predicted back (HTTP
    Retry-After header; gRPC retry-after trailer) instead of inviting an
    immediate blind retry. NON-RETRYABLE inside the fleet: the router
    picked the least-loaded replica, so every other replica is at least
    as overloaded — retrying the rest would amplify the overload
    (docs/advanced-guide/overload.md)."""

    status_code = 429
    retry_after: float | None = None

    def __init__(self, message: str = "", retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = max(0.1, float(retry_after))


class EngineStoppedError(RuntimeError):
    """Raised by submit() on a dead or closed engine. A TYPE, not a
    string: the replica router's retry loop used to match
    "engine stopped" in str(e) and silently swallowed any RuntimeError
    that happened to contain it. Subclasses RuntimeError so callers that
    caught the old error keep working."""


class EngineDraining(RuntimeError):
    """Raised by submit() while the engine drains (rolling deploy):
    admission is closed but in-flight work runs to completion. 503 via
    the statusCodeResponder seam — the load balancer should retry the
    next pod, not this one. `retry_after` rides the response (HTTP
    Retry-After / gRPC trailer) so a client talking straight to the pod
    backs off for roughly a readiness-probe window instead of spinning.
    RETRYABLE inside the fleet: another replica may still be accepting
    (the router excludes draining replicas, but a drain can begin
    between pick and submit)."""

    status_code = 503
    retry_after: float | None = 5.0


class UnknownAdapterError(KeyError):
    """Raised by submit() when ``req.adapter`` names no resident adapter
    (gofr_tpu.lora). 404 via the statusCodeResponder seam — the OpenAI
    edge turns it into the model-not-found error envelope. A KeyError
    subclass so registry-shaped callers that probe with ``except
    KeyError`` keep working."""

    status_code = 404

    def __init__(self, name: str, resident=()):
        super().__init__(name)
        self.adapter = name
        self.resident = sorted(resident)

    def __str__(self) -> str:
        return (
            f"unknown adapter {self.adapter!r}; resident: "
            f"{self.resident or 'none'}"
        )


class PoisonedRequestError(RuntimeError):
    """Raised by GenRequest.stream()/tokens() when the fleet refused a
    request further failover: it was in flight across
    ``TPU_LLM_POISON_DEATHS`` replica deaths, which makes its payload the
    prime suspect for those crashes — retrying it again would let one
    request kill every replica in turn. 500 via the statusCodeResponder
    seam (gRPC surfaces INTERNAL): the caller must NOT retry the same
    payload (docs/advanced-guide/resilience.md)."""

    status_code = 500


def finite_guard(logits, toks):
    """Numerical-watchdog sentinel: replace each sampled token whose
    logits row contains NaN/Inf with ``-1`` — an id no sampler can
    produce (argmax and top-k indices are >= 0), so the sentinel rides
    the existing token fetch at zero extra transfer cost and the
    collector converts it into a replica death instead of streaming
    garbage with status 200. One cheap on-device reduction per sampled
    row, trivially amortized against the matmuls that produced the
    logits. Traced into the engine's jitted programs when
    ``TPU_LLM_NUMERIC_CHECK`` is on; module-level so tests drive it with
    hand-built NaN logits."""
    import jax.numpy as jnp

    ok = jnp.isfinite(logits).all(axis=-1)
    return jnp.where(ok, toks, jnp.int32(-1))


@dataclass(eq=False)  # identity semantics: requests are handles, and the
# engine's error path collects them in sets (dataclass __eq__ would make
# them unhashable and value-compared)
class GenRequest:
    prompt_tokens: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token: int = _EOS_DEFAULT
    # Overload-control identity (docs/advanced-guide/overload.md):
    # priority class "interactive" (latency-sensitive; may preempt batch
    # work under queue pressure) or "batch" (throughput work; absorbs
    # pressure via preemption and brownout clamping). Anything except
    # the literal "batch" is treated as interactive — the edge forwards
    # the X-GoFr-Priority header verbatim and a typo must degrade to the
    # latency-safe class, not an error.
    priority: str = "interactive"
    # Fair-queuing client id (X-GoFr-Client header / API key / caller's
    # choice). "" pools unattributed traffic into one anonymous client.
    client: str = ""
    # Explicit W3C trace context for callers whose submitting thread the
    # tracing contextvar does not reach (executor pools, user threads);
    # submit() prefers the live contextvar span when one is active.
    traceparent: str | None = None
    # Absolute wall deadline (time.perf_counter timebase). Past it the
    # engine cancels the request EVEN WHILE SLOTTED (finish_reason
    # "deadline") — a decode past its HTTP timeout burns chip time for a
    # client that already gave up. Handlers pass ctx.deadline here.
    deadline: float | None = None
    # Chaos-only payload marker: a fault spec armed with the same tag
    # fires exactly when THIS request's step dispatches (the
    # deterministic stand-in for a payload that crashes the step
    # program; gofr_tpu.resilience.faults). Empty for real traffic.
    tag: str = ""
    # Conversation id (X-GoFr-Session header; docs/advanced-guide/
    # kv-cache.md#sessions). On finish the full sequence's KV blocks
    # stay resident in the paged pool keyed by this id (spilled to host
    # RAM when cold), so the NEXT turn's prompt — which extends this
    # conversation — block-shares the whole history instead of
    # re-prefilling it. Empty = sessionless (blocks free at retire).
    session_id: str = ""
    # Grammar-constrained decoding (gofr_tpu.structured;
    # docs/advanced-guide/structured-decoding.md): a compiled
    # TokenGrammar. Every sampled token is masked to what the grammar's
    # current DFA state admits — the output is valid by construction —
    # and the per-slot state advances INSIDE the fused device programs,
    # so constrained and unconstrained requests share one program.
    # Requires the chunked scheduler; eos_token is taken from the
    # grammar when unset. None = unconstrained (zero new device work).
    grammar: Any = None
    # Multi-tenant LoRA adapter name (gofr_tpu.lora; docs/advanced-guide/
    # multi-tenancy.md): the resident adapter whose low-rank delta this
    # request decodes under. The OpenAI edge maps model=<adapter> / the
    # X-GoFr-Adapter header here. "" = the base model (gid 0 identity —
    # token-identical to an engine with no adapter support). Requires the
    # chunked scheduler and a LoRA-enabled engine (lora_slots > 0).
    adapter: str = ""
    # Synthetic-traffic marker (gofr_tpu.goodput): canary checks, shadow
    # probes, rollout bakes, and flight-record replays set probe=True so
    # the goodput ledger classes their chip time as `probe` waste rather
    # than tenant demand — and the quota gate waves them through (an
    # over-quota tenant must not block the canary that protects it).
    probe: bool = False
    id: int = field(default_factory=itertools.count().__next__)

    def __post_init__(self):
        self.out: queue.Queue = queue.Queue()
        self.cancelled = False
        # why the cancel happened — becomes the finish_reason when the
        # engine retires the request ("cancelled" for an explicit caller
        # cancel, "disconnect" when the serving edge detected a dead
        # peer; docs/advanced-guide/rollouts.md#client-disconnects)
        self.cancel_reason = "cancelled"
        # model version of the engine that last accepted this request —
        # stamped by LLMEngine.submit. Once the stream has emitted a
        # token, failover PINS to this version: a stream must never be
        # served tokens from two model versions (rollouts).
        self.engine_version: str | None = None
        self.emitted = 0
        self.capped = False  # engine reduced max_new_tokens to fit the cache
        self.browned = False  # brownout clamped max_new_tokens (batch class)
        self.preempted = 0  # times a slot was taken back for interactive work
        self._prompt_billed = False  # fairness ledger saw the prompt tokens
        self.finish_reason: str | None = None  # "eos" | "length" | "cancelled"
        #   | "shed" | "deadline" | "error" | "poison" ("failover"
        #   transiently marks a request rescued off a dying replica so
        #   drain paths skip it)
        self.submitted_at: float | None = None
        # -- failover state (gofr_tpu.resilience) --
        # tokens emitted since the last (re)submit: on replica death the
        # router re-seeds prompt_tokens + history as the continuation
        # prompt, so the failed-over stream resumes exactly where the
        # consumer left off (greedy streams are token-identical).
        self.history: list[int] = []
        self.retries = 0  # failover re-dispatches consumed
        # replica deaths this request was IN FLIGHT for (slotted,
        # prefilling, or riding a device snapshot at _die — queued-only
        # bystanders are not implicated). At TPU_LLM_POISON_DEATHS the
        # router refuses further failover (finish_reason "poison").
        self.deaths = 0
        # -- chunked-prefill scheduler state (engine-maintained) --
        self.prefill_pos = 0  # prompt tokens already appended to slot KV
        self.prefill_done = False  # all prompt tokens resident; decoding
        self.slot: int | None = None  # slot index while resident
        self._rows_hi = 0  # highest slot row ever written (prefix trim)
        # -- paged KV state (engine-maintained; kvcache.paged) --
        self._kv_limit = 0  # worst-case rows (CacheManager.reserve_tokens)
        self._kv_resv = 0  # admission block promise not yet bound to a slot
        self._kv_plan = None  # pinned seed plan not yet attached to a slot
        self._session_published = False  # end-of-turn radix publish done
        self._prefill_t0: float | None = None  # first chunk dispatch time
        self._load_acct = 0  # outstanding token estimate (router weighting)
        # -- grammar-constrained decoding (engine-maintained) --
        # _g_id: this engine's resident-grammar table slot (set at
        # submit; -1 while unconstrained). _g_state: HOST mirror of the
        # DFA state after every emitted token — feeds the drafter's
        # grammar filter and re-seeds the device state when a
        # continuation (preemption/failover) re-admits mid-output.
        self._g_id = -1
        self._g_state = 0
        # -- multi-tenant LoRA (engine-maintained; gofr_tpu.lora) --
        # _aid: the adapter pool gid this request's in-flight reference
        # pins (0 = base/identity, never refcounted). Re-resolved from
        # `adapter` on every submit — a failover continuation lands on a
        # replica whose pool may bind the name to a different gid.
        self._aid = 0
        # -- speculative decoding (gofr_tpu.spec; engine-maintained) --
        # acceptance-rate EMA driving the adaptive draft length, and the
        # plain-pass streak that paces the backed-off re-probe. Starts
        # optimistic: the first verify measures the request's real rate.
        self._spec_ema = 1.0
        self._spec_plain = 0
        # optimistic pipelining state: predicted-but-unconfirmed tokens
        # (one span per in-flight verify) the drafter extends so the
        # next verify can DISPATCH before the previous one is fetched —
        # the verify program chains tail/cursor from device state, so a
        # stale draft costs acceptance, never correctness
        self._spec_pending: list[int] = []
        self._spec_inflight = 0
        # -- observability (engine-maintained; read by debug/stats/traces) --
        self.phase = "new"  # new -> queued -> prefill -> decode -> done
        self.prefix_hit = False
        self.admitted_at: float | None = None
        self.first_token_at: float | None = None
        self.span = None  # detached llm.request span (engine has a tracer)
        self._observed = False  # terminal observability emitted (idempotence)
        # journey accounting: hop counts every re-admission after the
        # first (failover re-submit, preemption continuation) so the
        # wide event reads "hop 2 of journey J"; journey_id pins the
        # trace id of the FIRST submit and survives kills — the handle a
        # cross-process stitch is queried by.
        self.hop = 0
        self.journey_id: str | None = None
        # -- goodput attribution (gofr_tpu.goodput; engine-maintained) --
        # _chip: chip-seconds attributed to this request by waste class
        # (useful/padding/spec_reject/replay/probe) — rolled into the
        # wide event, flight record, and OpenAI usage block at finish.
        # _replay_pos: prompt positions below this index were already
        # computed once (preemption/failover continuation re-prefill) —
        # the ledger classes their re-prefill as `replay`, not `useful`.
        self._chip: dict[str, float] = {}
        self._replay_pos = 0

    # -- consumption ------------------------------------------------------
    def _raise_terminal(self) -> None:
        """End-of-stream classification: a poison refusal is an ERROR the
        caller must see (500/INTERNAL — the payload is implicated in
        replica deaths and will not be retried), not a quietly short
        stream. Every other finish reason keeps the legacy
        truncate-and-return contract."""
        if self.finish_reason == "poison":
            raise PoisonedRequestError(
                f"request {self.id} implicated in {self.deaths} replica "
                "deaths; failover refused (do not retry this payload)"
            )

    def _consumer_gone(self) -> None:
        """The consuming generator was CLOSED before the stream finished —
        the serving edge detected a dead peer (HTTP broken pipe, gRPC
        context done) or the caller abandoned the iterator. Either way
        nobody will read another token: cancel so the engine frees the
        slot and credits load_tokens instead of decoding to completion
        for a connection that no longer exists."""
        if self.finish_reason is None and not self.cancelled:
            self.cancel(reason="disconnect")

    def stream(self, timeout: float = 60.0) -> Iterator[int]:
        """Yield token ids until the engine signals completion."""
        try:
            while True:
                item = self.out.get(timeout=timeout)
                if item is None:
                    self._raise_terminal()
                    return
                if isinstance(item, list):
                    yield from item
                else:
                    yield item
        except GeneratorExit:
            self._consumer_gone()
            raise

    async def astream(self, timeout: float = 60.0):
        import asyncio

        loop = asyncio.get_running_loop()
        try:
            while True:
                item = await loop.run_in_executor(None, lambda: self.out.get(timeout=timeout))
                if item is None:
                    self._raise_terminal()
                    return
                if isinstance(item, list):
                    for t in item:
                        yield t
                else:
                    yield item
        except GeneratorExit:
            self._consumer_gone()
            raise

    def cancel(self, reason: str = "cancelled") -> None:
        self.cancel_reason = reason
        self.cancelled = True

    def tokens(self, timeout: float = 60.0) -> list[int]:
        return list(self.stream(timeout=timeout))


class LLMEngine:
    _FETCH_FAIL_LIMIT = 3  # consecutive fetch failures before full reset
    _PREEMPT_CAP = 2  # max evictions per batch request (then it keeps its slot)
    # plain decode chunks bought by one failed clean-pipe drafting probe
    # (speculative mode): the chunk pipeline then drains and speculation
    # re-probes — ~one exposed fetch RTT per this many chunks of overhead
    _SPEC_REPROBE_CHUNKS = 16

    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 32,
        max_seq_len: int = 512,
        prefill_buckets: tuple[int, ...] = (16, 64, 128),
        decode_chunk: int = 8,
        prefill_chunk: int | None = None,
        step_token_budget: int | None = None,
        speculative: bool | None = None,
        spec_draft: int | None = None,
        lookahead: int = 3,
        admit_cap: int = 8,
        admit_delay_ms: float = 40.0,
        mesh=None,
        param_specs: Any = None,
        tp_overlap: bool | None = None,
        role: str = "",
        device=None,
        max_queue: int | None = None,
        ttft_deadline_ms: float | None = None,
        fair_queuing: bool | None = None,
        fair_weights: dict | None = None,
        fair_ledger=None,
        preemption: bool | None = None,
        shed_predicted_wait_s: float | None = None,
        brownout_wait_s: float | None = None,
        brownout_max_new: int | None = None,
        brownout_hold_s: float | None = None,
        step_watchdog_s: float | None = None,
        numeric_check: bool | None = None,
        constrained: bool | None = None,
        constrained_grammars: int | None = None,
        lora_slots: int | None = None,
        lora_rank: int | None = None,
        fault_injector=None,
        logger=None,
        metrics=None,
        tracer=None,
        warmup: bool = True,
        quantize: bool = False,
        kv_window: int | None = None,
        prefix_cache_mb: float = 0.0,
        kv_paged: bool | None = None,
        kv_block: int | None = None,
        kv_pool_blocks: int | None = None,
        kv_int8: bool | None = None,
        session_mb: float | None = None,
        host_cache_mb: float | None = None,
        kv_label: str = "llm",
        version: str = "v1",
        slo=None,
        slo_tenants: dict | None = None,
        flight_records: int | None = None,
        flight_redact: bool | None = None,
        blackbox_dir: str | None = None,
        blackbox_interval_s: float | None = None,
        anomaly: bool | None = None,
        wide_event_sample: int | None = None,
        goodput: bool | None = None,
        quotas: dict | None = None,
        usage_meter=None,
        usage_window_s: float | None = None,
    ):
        import jax
        import jax.numpy as jnp

        from .kvcache import CacheManager
        from .models.transformer import decode_chunk as chunk_fn
        from .models.transformer import prefill
        from .profiling import default_registry, instrument_jit
        from .profiling import mfu as mfu_mod
        from .utils import enable_compilation_cache

        enable_compilation_cache(logger=logger)

        if param_specs is not None and "unembed" in params and "unembed" not in param_specs:
            # untied-head (Llama) params: untied-ness lives in the pytree,
            # not the config, and callers routinely build specs with
            # sharding.param_specs(cfg, mesh) defaults — patch in embed's
            # spec (same [vocab, d] layout) instead of crashing shard_params
            param_specs = {**param_specs, "unembed": param_specs["embed"]}
        if quantize:
            from .models.quant import is_quantized, quantize_param_specs, quantize_params

            # int8 weights halve the HBM stream decode is bound by
            # (VERDICT r2: 5.0 GB bf16 -> 2.5 GB); no-op if already quantized
            # (a jitted identity could still copy the tree in HBM, so skip).
            if not is_quantized(params):
                params = instrument_jit(
                    "llm.quantize_params",
                    lambda p: quantize_params(p, cfg.dtype),
                    model=kv_label, metrics=metrics,
                )(params)
            if param_specs is not None:
                param_specs = quantize_param_specs(param_specs)
        self.quantized = quantize

        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = max_seq_len
        self.prefill_buckets = tuple(sorted(b for b in prefill_buckets if b <= max_seq_len))
        self.decode_chunk = decode_chunk
        self.lookahead = max(1, lookahead)
        self.admit_cap = min(admit_cap, slots)
        self.admit_delay = admit_delay_ms / 1000.0
        # -- token-budget step scheduler (chunked prefill) ----------------
        # step_token_budget bounds the TOTAL tokens packed into one device
        # step: the active slots' decode chunk is charged first (decode
        # always rides — it is the latency-critical work the budget
        # exists to protect) and prefill chunks coalesce into whatever
        # remains, floored at one chunk so a step always makes progress;
        # 0 restores the monolithic wave scheduler. prefill_chunk caps
        # the chunk compile shape; the configured buckets survive only as
        # the available chunk shapes, so short prompts keep their tight
        # compile shapes.
        import os as _os

        if step_token_budget is None:
            step_token_budget = int(
                _os.environ.get("TPU_LLM_STEP_TOKEN_BUDGET", "256")
            )
        if prefill_chunk is None:
            prefill_chunk = int(_os.environ.get("TPU_LLM_PREFILL_CHUNK", "64"))
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.step_token_budget = max(0, int(step_token_budget))
        self.chunked = self.step_token_budget > 0
        shapes = {min(b, self.prefill_chunk) for b in self.prefill_buckets}
        shapes.discard(0)
        self.chunk_shapes = tuple(sorted(shapes)) or (
            min(self.prefill_chunk, max_seq_len),
        )
        # -- speculative decoding (gofr_tpu.spec;
        # docs/advanced-guide/speculative-decoding.md) --------------------
        # A host-side n-gram/prompt-lookup drafter proposes up to
        # spec_draft tokens per decoding slot; ONE fused verify program
        # scores all draft+1 positions against the slot KV, samples each
        # with the regular top-k machinery, accepts the longest agreeing
        # prefix ON DEVICE (tail/cursors stay device-resident), and rolls
        # the KV cursor back past rejected rows. Greedy spec-on is
        # token-identical to spec-off; temperature>0 is
        # distribution-preserving (Leviathan rejection sampling for a
        # deterministic drafter). OFF by default: disabled, no verify
        # program exists and no scheduler path changes — a true no-op.
        if speculative is None:
            speculative = _os.environ.get("TPU_LLM_SPEC", "0") not in ("", "0")
        self.speculative = bool(speculative)
        if spec_draft is None:
            spec_draft = int(_os.environ.get("TPU_LLM_SPEC_DRAFT", "") or 0)
        if not spec_draft:
            from .spec import SPEC_DRAFT_DEFAULT

            spec_draft = SPEC_DRAFT_DEFAULT
        # verify transiently writes draft+1 rows past a slot's length;
        # submit()'s decode-room cap reserves 2*decode_chunk rows of
        # slack, so the draft must fit it (dense scatters drop overflow,
        # but a silent clamp beats silent garbage)
        self.spec_draft = (
            max(1, min(int(spec_draft), 2 * decode_chunk))
            if self.speculative else 0
        )
        # SLO-aware overload control (both optional, both mutable at
        # runtime): max_queue bounds requests waiting for a slot — beyond
        # it submit() raises EngineOverloaded (-> 429) instead of letting
        # p99 grow with an unbounded closed-loop queue; ttft_deadline_ms
        # sheds a request still queued when its first token could no
        # longer arrive in time (finish_reason "shed").
        self.max_queue = max_queue
        self.ttft_deadline = (
            ttft_deadline_ms / 1000.0 if ttft_deadline_ms else None
        )
        self.rejected = 0  # submit-time cap rejections
        self.shed = 0  # deadline sheds at admission
        self.deadline_cancels = 0  # mid-flight deadline cancellations
        # -- overload control (gofr_tpu.resilience.overload;
        # docs/advanced-guide/overload.md) --------------------------------
        # Per-client weighted fair queuing: _waiting is ordered
        # (priority class, ledger counter, submit order) instead of FIFO,
        # so a flood from one client cannot starve another's weighted
        # share. ReplicatedLLMEngine passes ONE shared ledger to every
        # replica (fleet-wide fairness); a bare engine builds its own.
        from .resilience import FairLedger, OverloadController

        if fair_queuing is None:
            fair_queuing = _os.environ.get("TPU_LLM_FAIR", "1") != "0"
        self.ledger = None
        if fair_queuing:
            self.ledger = (
                fair_ledger if fair_ledger is not None
                else FairLedger(fair_weights)
            )
        # Priority preemption: under interactive queue pressure a slotted
        # batch request is preempted — its slot freed NOW, its emitted
        # tokens folded into a continuation prompt and requeued (the PR 5
        # failover re-seed, so greedy streams resume token-identically).
        if preemption is None:
            preemption = _os.environ.get("TPU_LLM_PREEMPT", "1") != "0"
        self.preemption = bool(preemption)
        self.preemptions = 0  # batch slots taken back for interactive work
        # Adaptive shedding + brownout: predicted queue wait (queued
        # tokens / measured step throughput) drives early 429s with a
        # computed Retry-After, and sustained pressure clamps batch-class
        # max_new_tokens BEFORE anything is shed (degrade, then shed).
        if shed_predicted_wait_s is None:
            shed_predicted_wait_s = float(
                _os.environ.get("TPU_LLM_SHED_WAIT_S", "0") or 0.0
            )
        if brownout_wait_s is None:
            brownout_wait_s = float(
                _os.environ.get("TPU_LLM_BROWNOUT_WAIT_S", "0") or 0.0
            )
        if brownout_max_new is None:
            brownout_max_new = int(
                _os.environ.get("TPU_LLM_BROWNOUT_MAX_NEW", "0") or 0
            )
        if brownout_hold_s is None:
            brownout_hold_s = float(
                _os.environ.get("TPU_LLM_BROWNOUT_HOLD_S", "2.0") or 0.0
            )
        self.overload = OverloadController(
            shed_wait_s=shed_predicted_wait_s,
            brownout_wait_s=brownout_wait_s,
            brownout_max_new=brownout_max_new,
            brownout_hold_s=brownout_hold_s,
        )
        self.sheds_predicted = 0  # predicted-wait 429s
        self.brownout_clamped = 0  # batch requests clamped while browned out
        self._tput_ema: float | None = None  # measured tokens/s (EMA)
        # -- resilience (gofr_tpu.resilience; docs/advanced-guide/resilience.md)
        from .resilience import Heartbeat, default_injector

        # fault-injection seams: disarmed cost is one dict lookup per
        # check; tests/chaos pass their own injector, production uses the
        # process default (armable via TPU_LLM_FAULTS)
        self.faults = fault_injector if fault_injector is not None else default_injector()
        # heartbeats the step watchdog monitors: the scheduler's blocking
        # dispatch section and the collector's device fetch
        self._hb_dispatch = Heartbeat()
        self._hb_fetch = Heartbeat()
        if step_watchdog_s is None:
            step_watchdog_s = float(
                _os.environ.get("TPU_LLM_STEP_WATCHDOG_S", "0") or 0.0
            )
        self.step_watchdog_s = max(0.0, float(step_watchdog_s))
        self.watchdog = None  # started after the engine threads
        # Numerical watchdog (docs/advanced-guide/resilience.md): trace
        # the finite_guard sentinel into every sampling program so
        # NaN/Inf logits become a replica death with reason "numerical"
        # instead of a garbage stream with status 200. On by default —
        # the on-device cost is one isfinite reduction per sampled row
        # and the sentinel rides fetches that happen anyway.
        if numeric_check is None:
            numeric_check = _os.environ.get("TPU_LLM_NUMERIC_CHECK", "1") != "0"
        self.numeric_check = bool(numeric_check)
        self.numerical_trips = 0  # non-finite logits -> replica death
        self.errored = 0  # requests finished "error"/"poison" (bake signal)
        self._draining = False  # drain(): admission closed, work finishes
        self._died = False  # _die ran (idempotence + stale-emission guard)
        self._die_guard = threading.Lock()
        self.died_reason: str | None = None
        # replica-failover seam: ReplicatedLLMEngine sets this; _die hands
        # it every recoverable in-flight/queued request instead of
        # error-draining them
        self.failover_hook = None
        self.logger = logger
        self.metrics = metrics
        self.tracer = tracer
        # kv_label doubles as the engine's metric/trace label (register_llm
        # passes the registered model name; replicas get a /rN suffix)
        self.label = kv_label
        # disaggregated serving role ("prefill" | "decode" | "" for a
        # colocated engine): rides the phase histograms as a `role` label
        # so TTFT/TPOT split per pool (docs/advanced-guide/
        # sharded-serving.md). Empty = no label, series unchanged.
        self.role = str(role)
        self._role_labels = {"role": self.role} if self.role else {}
        # model-version label (docs/advanced-guide/rollouts.md): which
        # weight set this engine serves. Streams pin to it across
        # failover; the wide-event line and the per-version request
        # counter carry it.
        self.version = str(version)
        self.disconnect_cancels = 0  # dead-peer cancellations (edges)
        if metrics is not None:
            _register_phase_metrics(metrics)
            metrics.set_gauge(
                "app_llm_model_version_info", 1.0,
                model=self.label, version=self.version,
            )
        # -- per-tenant SLO engine (docs/advanced-guide/
        # observability-serving.md#slo) ----------------------------------
        # Declared targets -> goodput counters + 5m/1h burn-rate gauges.
        # `slo` is an SLOPolicy/dict from register_llm (which merges the
        # TPU_LLM_SLO_* config knobs with per-model overrides); a bare
        # engine falls back to the process env so tests and scripts can
        # arm it without an app. None/inactive -> zero per-request cost.
        from .metrics.slo import SLOPolicy, SLOTracker

        policy = SLOPolicy.coerce(slo)
        if policy is None:
            policy = SLOPolicy(
                ttft_ms=float(_os.environ.get("TPU_LLM_SLO_TTFT_MS", "") or 0) or None,
                tpot_ms=float(_os.environ.get("TPU_LLM_SLO_TPOT_MS", "") or 0) or None,
                availability=float(
                    _os.environ.get("TPU_LLM_SLO_AVAILABILITY", "") or 0
                ) or None,
            )
        self.slo = None
        if policy.active():
            self.slo = SLOTracker(
                policy, metrics, self.label,
                tenant_overrides={
                    str(t): SLOPolicy.coerce(p)
                    for t, p in (slo_tenants or {}).items()
                },
            )
        # recent-window phase samples (seconds) for stats()/debug — exact
        # p50/p99 over the last ~512 observations, deque-append cheap
        from .metrics import RollingWindow

        self._phases = {
            "queue_wait": RollingWindow(),
            "ttft": RollingWindow(),
            "time_per_output_token": RollingWindow(),
            "decode_step": RollingWindow(),
            # unified-step dispatch->fetch wall (chunked scheduler only)
            "step": RollingWindow(),
        }
        # MFU/roofline accounting: analytic model FLOPs computed ONCE from
        # the architecture (gofr_tpu.profiling.mfu), combined per prefill
        # wave / decode chunk with measured dispatch->fetch wall time and
        # the device peak. Windows exist even without a metrics manager so
        # stats()["mfu"] and bench.py work on bare engines.
        self._mfu_mod = mfu_mod
        self._costs = mfu_mod.model_costs(cfg, quantized=quantize)
        _dev = jax.devices()[0] if jax.devices() else None
        _platform = getattr(_dev, "platform", "")
        _kind = getattr(_dev, "device_kind", "")
        self._peak_flops = mfu_mod.device_peak_flops(_platform, _kind)
        self._hbm_bw = mfu_mod.device_hbm_bandwidth(_platform, _kind)
        self._n_chips = int(mesh.size) if mesh is not None else 1
        self._mfu_windows = {"prefill": RollingWindow(), "decode": RollingWindow()}
        self._roofline_windows = {"prefill": RollingWindow(), "decode": RollingWindow()}
        self._tok_chip_window = RollingWindow()
        self._registry = default_registry()
        self.warmup_s: float | None = None
        self._wide_events: list[dict] = []  # appended under _lock, drained outside
        # -- incident flight recorder (gofr_tpu.flightrec; docs/advanced-
        # guide/incident-debugging.md) -----------------------------------
        # Per-request black-box ring (started at submit, finalized on
        # every terminal path incl. _die), an incident bundle dumper
        # (inert unless GOFR_BLACKBOX_DIR / blackbox_dir is set), and
        # rolling-baseline perf-anomaly detectors whose flag transitions
        # are themselves bundle triggers.
        from .flightrec import (
            WIDE_EVENTS_KEEP,
            AnomalyDetector,
            BlackboxDumper,
            FlightRecorder,
        )

        self.flightrec = FlightRecorder(flight_records, redact=flight_redact)
        self.blackbox = BlackboxDumper(
            blackbox_dir, min_interval_s=blackbox_interval_s,
            logger=logger, metrics=metrics, label=self.label,
        )
        if self.slo is not None:
            # the fast-burn 0 -> 1 flip is a bundle trigger: capture the
            # engine while the budget-burning requests are still visible
            self.slo.on_fast_burn = lambda: self._incident(
                "slo_fast_burn",
                reason=f"error-budget fast burn tripped on {self.label}",
            )
        if anomaly is None:
            anomaly = _os.environ.get("TPU_LLM_ANOMALY", "1") not in ("", "0")
        self.anomaly = None
        if anomaly:
            self.anomaly = AnomalyDetector(
                metrics, self.label,
                on_flag=lambda sig, val, mean: self._incident(
                    "anomaly",
                    reason=(
                        f"{sig} sustained deviant: {val:.3f} vs baseline "
                        f"mean {mean:.3f}"
                    ),
                ),
            )
        # wide-event sampling (satellite of the flight recorder): 1-in-N
        # request lines under load — incident/error/failover lines always
        # emit. The FULL stream lands in _wide_retained either way, so a
        # bundle's wide-event section never has sampling holes.
        if wide_event_sample is None:
            wide_event_sample = int(
                _os.environ.get("TPU_LLM_WIDE_EVENT_SAMPLE", "") or 1
            )
        self._wide_sample = max(1, int(wide_event_sample))
        self._wide_seq = 0
        self._wide_retained: deque = deque(maxlen=WIDE_EVENTS_KEEP)
        # -- goodput ledger + per-tenant usage metering (gofr_tpu.goodput;
        # docs/advanced-guide/cost-accounting.md) -------------------------
        # Chip-time attribution at the fetch seam (every device window
        # split across its lanes into the waste taxonomy), rolling
        # per-tenant usage windows (shared fleet-wide when replicated
        # serving passes usage_meter=), and hard token-rate quotas
        # enforced at admission with a Retry-After priced from the
        # tenant's measured window.
        from .goodput import GoodputLedger, QuotaGate, UsageMeter
        from .goodput import parse_quota_spec as _parse_quota

        if goodput is None:
            goodput = _os.environ.get("TPU_LLM_GOODPUT", "1") not in ("", "0")
        self.goodput = None
        self.usage = None
        self.quota = None
        self.quota_sheds = 0
        if goodput:
            if usage_window_s is None:
                usage_window_s = float(
                    _os.environ.get("TPU_LLM_USAGE_WINDOW_S", "") or 60.0
                )
            self.usage = (
                usage_meter if usage_meter is not None
                else UsageMeter(window_s=usage_window_s)
            )
            self.goodput = GoodputLedger(
                metrics=metrics, label=self.label,
                version_fn=lambda: self.version, usage=self.usage,
            )
            q = _parse_quota(_os.environ.get("TPU_LLM_TENANT_QUOTA_TOK_S"))
            for tenant, rate in (quotas or {}).items():
                try:
                    q[str(tenant)] = float(rate)
                except (TypeError, ValueError):
                    continue
            self.quota = QuotaGate(q, self.usage)
        # KV layout/residency/reuse policy lives in the kvcache subsystem:
        # rolling ring for sliding-window models (slot memory O(window)),
        # dense slab otherwise; optional prompt-prefix reuse at admission.
        # kv_label distinguishes metric series: register_llm passes the
        # registered model name, and replicated serving suffixes a replica
        # index — otherwise N replicas' resident-bytes gauges share one
        # label set and clobber each other on /metrics.
        # UNIFIED capacity accounting: every append width one device
        # program can dispatch — the decode chunk, the chunked-prefill
        # chunk shapes, the speculative verify width — goes to the
        # CacheManager ONCE as append_widths; the rolling-ring capacity
        # and the paged block reservation both derive from the same
        # max() there, replacing the per-feature slack arithmetic the
        # chunked-prefill and speculative-verify paths each used to
        # layer onto the ring bound.
        append_widths = [decode_chunk]
        if self.chunked:
            append_widths.extend(self.chunk_shapes)
        if self.speculative:
            append_widths.append(self.spec_draft + 1)
        if kv_paged is None:
            from .kvcache import paged_default

            kv_paged = paged_default()
        self.kv = CacheManager(
            cfg, slots, max_seq_len, decode_chunk,
            window=kv_window, prefix_cache_mb=prefix_cache_mb,
            append_widths=tuple(append_widths),
            paged=kv_paged, block=kv_block, pool_blocks=kv_pool_blocks,
            kv_int8=kv_int8, session_mb=session_mb,
            host_cache_mb=host_cache_mb,
            metrics=metrics, model=kv_label,
        )
        self._sharded = mesh is not None and param_specs is not None
        self.mesh = mesh if self._sharded else None
        # tensor-parallel degree (docs/advanced-guide/sharded-serving.md):
        # the "model" axis of the replica's submesh; 1 for single-chip.
        # Exported as app_llm_tp_degree so dashboards see the fleet shape.
        self.tp_degree = (
            int(dict(mesh.shape).get("model", 1)) if self._sharded else 1
        )
        # Collective-compute overlap (ROADMAP raw-speed side quest; ISSUE
        # 12): the sharded DECODE path stores weights sharded and
        # all-gathers the NEXT layer's shard while the current layer's
        # matmul runs (parallel.sharding.replicate_gather through
        # models.transformer._layer_scan). Also the numerics lever that
        # pins TP==TP1 greedy token equality: gathered-weight compute has
        # no partial-product psum, hence no reduction-order drift.
        if tp_overlap is None:
            tp_overlap = _os.environ.get("TPU_LLM_TP_OVERLAP", "1") != "0"
        self.tp_overlap = bool(tp_overlap) and self.tp_degree > 1
        if metrics is not None:
            metrics.set_gauge(
                "app_llm_tp_degree", float(self.tp_degree), model=kv_label,
            )
            metrics.set_gauge(
                "app_llm_moe_experts",
                float(getattr(cfg, "n_experts", 0) or 0), model=kv_label,
            )
        self._tp_gather = None
        if self.tp_overlap:
            from .parallel.sharding import replicate_gather

            self._tp_gather = replicate_gather(mesh)
        if mesh is not None and param_specs is not None:
            from .parallel.sharding import shard_params

            t0_gather = time.perf_counter()
            params = shard_params(params, mesh, param_specs)
            # initial shard placement: the weight-scatter wall a replica
            # pays once at build (phase label mirrors the per-layer
            # gathers the decode path then overlaps)
            if metrics is not None:
                metrics.record_histogram(
                    "app_llm_collective_seconds",
                    time.perf_counter() - t0_gather,
                    model=kv_label, phase="weight_shard",
                )
        elif device is not None:
            # replica pinning (data-parallel serving): committing params to
            # a device makes every jitted call and its donated state follow
            params = jax.device_put(params, device)
        else:
            params = jax.device_put(params)

        # -- multi-tenant LoRA adapter pool (gofr_tpu.lora;
        # docs/advanced-guide/multi-tenancy.md) ---------------------------
        # lora_slots > 0 merges stacked zero-initialized (A, B) tables and
        # a per-slot adapter-id vector INTO the params pytree, so the same
        # fused programs serve every tenant via a batched gather — no
        # per-tenant compile, and a hot-load is one table-slice rewrite.
        # Chunked-scheduler only, like constrained decoding: the wave path
        # packs prefill rows != slots, so adapter ids cannot ride it.
        if lora_slots is None:
            lora_slots = int(_os.environ.get("TPU_LLM_LORA_SLOTS", "0") or 0)
        if lora_rank is None:
            lora_rank = int(_os.environ.get("TPU_LLM_LORA_RANK_MAX", "8") or 8)
        self.lora_slots = max(0, int(lora_slots)) if self.chunked else 0
        self.lora_rank = max(1, int(lora_rank))
        if self.lora_slots:
            from . import lora as lora_mod
            from .lora import AdapterPool

            self._lora_mod = lora_mod
            tables = lora_mod.zero_tables(cfg, self.lora_slots, self.lora_rank)
            aids0 = jnp.zeros((slots,), jnp.int32)
            if self._sharded:
                from jax.sharding import NamedSharding, PartitionSpec as _P

                from .parallel.sharding import shard_params as _shard

                tables = _shard(tables, mesh, lora_mod.table_specs(tables))
                aids0 = jax.device_put(aids0, NamedSharding(mesh, _P(None)))
            elif device is not None:
                tables = jax.device_put(tables, device)
                aids0 = jax.device_put(aids0, device)
            else:
                tables = jax.device_put(tables)
                aids0 = jax.device_put(aids0)
            # merged AFTER the quantize block on purpose: the tables stay
            # f32 (lora.zero_tables) and quantize_params only touches
            # _QUANT_KEYS, but this ordering makes it structural
            params = {
                **params,
                "layers": {**params["layers"], **tables},
                "aids": aids0,
            }
            self._lora_pool = AdapterPool(self.lora_slots)
            self._aids_host = [0] * slots
            self._aids_dirty = False
            # staging programs compile lazily per table shape (6 targets x
            # (a, b)); the gid is traced so every load reuses them
            self._lora_set_ops: dict = {}
        self.params = params
        self.device = device

        # -- jitted programs (one dispatch each) --------------------------
        topk = min(64, cfg.vocab_size)

        _numeric_check = self.numeric_check

        def _sample_raw(logits, temps, key):
            """Greedy for temp==0; temperature sampling restricted to the
            top-k logits otherwise. Full-vocab categorical would generate
            batch x vocab Gumbel draws per step (millions of threefry
            rounds for a 256k vocab) and dominates decode time; top-k keeps
            the RNG work at batch x 64."""
            greedy = jnp.argmax(logits, axis=-1)
            topv, topi = jax.lax.approx_max_k(logits, topk)
            local = jax.random.categorical(
                key, topv / jnp.maximum(temps, 1e-4)[:, None], axis=-1
            )
            sampled = jnp.take_along_axis(topi, local[:, None], axis=1)[:, 0]
            return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)

        def _sample(logits, temps, key):
            """_sample_raw plus the numerical watchdog: a row whose logits
            went NaN/Inf samples the -1 sentinel instead (finite_guard) —
            the collector converts it to a replica death before anything
            is emitted."""
            out = _sample_raw(logits, temps, key)
            return finite_guard(logits, out) if _numeric_check else out

        # -- grammar-constrained sampling (gofr_tpu.structured;
        # docs/advanced-guide/structured-decoding.md) ---------------------
        # gtab [G, Smax, V] int32 is the resident-grammar transition
        # table (entry < 0 = token not admitted in that state); gid [B]
        # selects each lane's grammar (-1 = unconstrained) and gstate [B]
        # its current DFA state. Masking uses a large-negative bias, not
        # -inf (an all-masked padding row must stay NaN-free), and the
        # watchdog guard runs on the RAW logits — a grammar mask is not a
        # numerical fault. Unconstrained lanes take their logits
        # UNTOUCHED (a jnp.where select, not a +0 bias), which is what
        # pins mixed-batch token-identity with the unconstrained programs.
        _G_NEG = jnp.float32(-1e30)

        def _g_rows(gtab, gid, gstate):
            G, Smax = gtab.shape[0], gtab.shape[1]
            rows = gtab[
                jnp.clip(gid, 0, G - 1), jnp.clip(gstate, 0, Smax - 1)
            ]  # [B, V] next state per token, or < 0
            on = (gid >= 0) & (gstate >= 0) & (gstate < Smax)
            return rows, on

        def _g_mask(logits, rows, on):
            return jnp.where(on[:, None] & (rows < 0), _G_NEG, logits)

        def _g_sample(logits, temps, key, gtab, gid, gstate):
            """One masked sample + DFA advance for per-lane grammar
            states: the stateful sampler the constrained program family
            threads through decode chunks (models.transformer
            sample_state seam), unified steps, and verify positions."""
            rows, on = _g_rows(gtab, gid, gstate)
            out = _sample_raw(_g_mask(logits, rows, on), temps, key)
            out = finite_guard(logits, out) if _numeric_check else out
            nxt = jnp.take_along_axis(
                rows, jnp.clip(out, 0)[:, None], axis=1
            )[:, 0]
            return out, jnp.where(on, nxt, gstate)

        # last-token logits ride the prefill programs whenever ANY prefix
        # index can serve exact hits from them: the contiguous PrefixCache
        # or the paged radix tree (kvcache.paged)
        keep_logits = self.kv.prefix is not None or (
            self.kv.paged and self.kv.share
        )

        def _prefill_op(params, pack, rng):
            """pack [nb, bucket+2] int32: tokens | lengths | temps-as-bits.
            One packed host->device transfer per wave — through the axon
            tunnel every h2d array costs ~3.5 ms of host-blocking latency
            regardless of size, so the engine never ships loose vectors.
            For windowed configs the dense banded prefill is ring-packed to
            the rolling slot width; when the prefix cache is on, the last-
            token logits ride along so hits can re-sample first tokens."""
            tokens = pack[:, :-2]
            lengths = pack[:, -2]
            temps = jax.lax.bitcast_convert_type(pack[:, -1], jnp.float32)
            last_logits, cache = prefill(
                params, cfg, tokens, lengths,
                self.kv.prefill_cache_len(tokens.shape[1]),
            )
            cache = self.kv.pack_prefill(cache)
            rng, sub = jax.random.split(rng)
            first = _sample(last_logits, temps, sub)
            return first, cache, (last_logits if keep_logits else None), rng

        def _hit_first(logits, temps, rng):
            """First token for prefix-cache hits: the stored last-token
            logits sampled at each request's own temperature — greedy hits
            reproduce the uncached stream bit-for-bit."""
            rng, sub = jax.random.split(rng)
            return _sample(logits, temps, sub), rng

        def _make_chunk_op(K: int):
            def _chunk_op(params, tokens, cache, active, temps, rng):
                return chunk_fn(
                    params, cfg, tokens, cache, active, temps, rng,
                    n_steps=K, sample_fn=_sample, ring=self.kv.ring,
                    overlap=self._tp_gather,
                )

            return instrument_jit(
                f"llm.decode_chunk{K}", _chunk_op, model=self.label,
                metrics=metrics, donate_argnums=(2,),
            )

        M = self.admit_cap

        def _insert_many(slot_cache, new_cache, meta):
            """Copy new_cache row meta[1][i] into slot meta[0][i] for i < M.
            Padding entries duplicate entry 0 (idempotent rewrite)."""

            def body(c, xs):
                si, row = xs
                k = jax.lax.dynamic_update_slice(
                    c.k,
                    jax.lax.dynamic_slice_in_dim(new_cache.k, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                v = jax.lax.dynamic_update_slice(
                    c.v,
                    jax.lax.dynamic_slice_in_dim(new_cache.v, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                length = jax.lax.dynamic_update_slice(
                    c.length,
                    jax.lax.dynamic_slice_in_dim(new_cache.length, row, 1, axis=0),
                    (si,),
                )
                return c._replace(k=k, v=v, length=length), None

            cache, _ = jax.lax.scan(body, slot_cache, (meta[0], meta[1]))
            return cache

        def _admit_update(tail, active, temps, first, meta):
            """Scatter freshly-prefilled first tokens into the on-device
            chain tail and mark the slots active with their temperatures —
            admission never forces a host round trip. meta [3, M] int32:
            slot_idx | rows | temps-as-bits; padding entries repeat index 0
            (idempotent)."""
            slot_idx, rows = meta[0], meta[1]
            req_temps = jax.lax.bitcast_convert_type(meta[2], jnp.float32)
            tail = tail.at[slot_idx].set(first[rows])
            active = active.at[slot_idx].set(True)
            temps = temps.at[slot_idx].set(req_temps)
            return tail, active, temps

        # Every serving executable goes through the compile observatory:
        # per-signature compile wall time + cost_analysis into the process
        # registry (/.well-known/debug/compiles), app_jax_* metrics when a
        # manager is wired. Dispatch semantics (donation, shardings) are
        # identical to the bare jax.jit these wrappers replace.
        self._prefill_op = instrument_jit(
            "llm.prefill", _prefill_op, model=self.label, metrics=metrics,
        )
        # Two chunk lengths: the full chunk amortizes dispatch and is
        # chained eagerly to cover remaining demand (an 8-token completion
        # costs ~2 RTTs); the short variant (quarter length) only serves
        # tail ends where even one full chunk overshoots the whole batch's
        # remaining need (_dispatch).
        self._chunk_short = max(1, decode_chunk // 4)
        self._chunk_ops = {decode_chunk: _make_chunk_op(decode_chunk)}
        if self._chunk_short != decode_chunk:
            self._chunk_ops[self._chunk_short] = _make_chunk_op(self._chunk_short)
        self._insert_many = instrument_jit(
            "llm.insert_many", _insert_many, model=self.label,
            metrics=metrics, donate_argnums=(0,),
        )
        self._admit_update = instrument_jit(
            "llm.admit_update", _admit_update, model=self.label,
            metrics=metrics, donate_argnums=(0, 1, 2),
        )
        self._hit_first_op = (
            instrument_jit(
                "llm.hit_first", _hit_first, model=self.label, metrics=metrics,
            )
            if keep_logits else None
        )

        # -- unified step programs (token-budget scheduler) ---------------
        # ONE jitted program per chunk shape: gather the prefilling
        # slots' KV rows, append one chunk per row
        # (models.transformer.prefill_append), scatter the rows back,
        # activate rows whose prompt just completed (their first token
        # sampled from the chunk's last-token logits, merged into the
        # on-device tail — no host round trip), then, in the SAME
        # program, advance every active slot one decode chunk. Decode is
        # ALWAYS fused — rows that finish this step decode immediately
        # (no extra dispatch for the first chunk), and an all-inactive
        # decode part costs one bounded masked chunk during cold prefill
        # ramp only. Executable count: shapes x pow2-widths — it replaces
        # the monolithic path's buckets x widths prefill family plus its
        # separate insert/admit programs on the miss path.
        from .models.transformer import prefill_append

        _slots_oob = slots  # out-of-range slot index: scatters are dropped

        def _make_step_op(shape: int):
            K = decode_chunk

            def _step(params, cache, tail, active, temps, pack, meta, rng):
                """pack [nb, shape+3] int32: tokens | cursor | n_new |
                temp-bits. meta [2, nb] int32: slot (= `slots` for inert
                padding lanes) | finish flag. One packed h2d per step."""
                tokens = pack[:, :shape]
                cursors = pack[:, shape]
                n_new = pack[:, shape + 1]
                req_temps = jax.lax.bitcast_convert_type(
                    pack[:, shape + 2], jnp.float32
                )
                slot_idx, finish = meta[0], meta[1]
                # per-row adapter ids (LoRA engines only — static pytree
                # check): packed prefill lanes gather their slot's id; the
                # fused decode below reads the full per-slot vector itself
                aids_row = (
                    jnp.take(params["aids"], slot_idx, mode="clip")
                    if "aids" in params else None
                )
                # gather the target slots' resident rows (padding lanes
                # clip to a real slot but never write back)
                sub = cache._replace(
                    k=jnp.take(cache.k, slot_idx, axis=1, mode="clip"),
                    v=jnp.take(cache.v, slot_idx, axis=1, mode="clip"),
                    length=cursors,
                )
                logits, sub = prefill_append(
                    params, cfg, tokens, sub, cursors, n_new,
                    ring=self.kv.ring, aids=aids_row,
                )
                cache = cache._replace(
                    k=cache.k.at[:, slot_idx].set(sub.k, mode="drop"),
                    v=cache.v.at[:, slot_idx].set(sub.v, mode="drop"),
                    length=cache.length.at[slot_idx].set(
                        cursors + n_new, mode="drop"
                    ),
                )
                rng, sub_rng = jax.random.split(rng)
                first = _sample(logits, req_temps, sub_rng)
                fin_slot = jnp.where(finish == 1, slot_idx, _slots_oob)
                # Mid-prefill rows must deactivate their slot: the device
                # flag may still be True from the slot's PREVIOUS occupant
                # (nothing clears it at finish), and the decode merge
                # advances length for active slots — on a rolling ring the
                # stale advance between two appends can wrap past the
                # capacity slack and overwrite this prompt's in-window
                # rows. (Writes BEFORE the first chunk are harmless: the
                # first append resets length, and rows beyond it are
                # position-masked.) Disjoint from fin_slot — a pack row
                # either finishes or not.
                mid_slot = jnp.where(finish == 1, _slots_oob, slot_idx)
                active = active.at[mid_slot].set(False, mode="drop")
                tail = tail.at[fin_slot].set(first, mode="drop")
                active = active.at[fin_slot].set(True, mode="drop")
                temps = temps.at[fin_slot].set(req_temps, mode="drop")
                kept = logits if keep_logits else None
                toks, last, cache, rng = chunk_fn(
                    params, cfg, tail, cache, active, temps, rng,
                    n_steps=K, sample_fn=_sample, ring=self.kv.ring,
                    overlap=self._tp_gather,
                )
                return first, kept, toks, last, cache, active, temps, rng

            name = f"llm.step_p{shape}_d{K}"
            return instrument_jit(
                name, _step, model=self.label, metrics=metrics,
                donate_argnums=(1, 2, 3, 4),
            )

        self._step_ops: dict[int, Any] = {}
        if self.chunked:
            for shape in self.chunk_shapes:
                self._step_ops[shape] = _make_step_op(shape)

        # -- fused speculative verify program (gofr_tpu.spec) -------------
        # ONE full-batch program in the step family (llm.step_v{W}):
        # score all W = draft+1 positions of every selected slot's draft
        # in one write-then-attend forward pass
        # (models.transformer.verify_chunk), sample each position with
        # the engine's regular _sample, accept the longest agreeing
        # prefix ON DEVICE, advance tail/length to the accepted state —
        # so the device batch state stays chained exactly as decode
        # chunks leave it, and the host fetch only feeds emission and the
        # drafter. Rejected rows stay above the rolled-back cursor,
        # masked until overwritten (ops.chunk_prefill_attention's
        # rollback contract). Built ONLY when speculation is on: spec-off
        # engines compile and register nothing new.
        self.drafter = None
        self._verify_op = None
        if self.speculative:
            from .models.transformer import verify_chunk as verify_fn
            from .spec import NGramDrafter

            self.drafter = NGramDrafter()
            Kd = self.spec_draft
            Wv = Kd + 1

            def _verify(params, cache, tail, temps, pack, rng):
                """pack [S, Kd+2] int32: draft tokens | n_draft | selected.
                Unselected lanes write nothing (n_in 0 drops every
                scatter index) and keep their tail/length — the program
                is safe to run over the full slot batch."""
                drafts = pack[:, :Kd]
                n_draft = pack[:, Kd]
                sel = pack[:, Kd + 1] == 1
                n_in = jnp.where(sel, n_draft + 1, 0)
                toks = jnp.concatenate([tail[:, None], drafts], axis=1)
                logits, new_cache = verify_fn(
                    params, cfg, toks, cache, cache.length, n_in,
                    ring=self.kv.ring, aids=params.get("aids"),
                )
                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, Wv)
                ys = jnp.stack(
                    [
                        _sample(logits[:, j], temps, keys[j])
                        for j in range(Wv)
                    ],
                    axis=1,
                )  # [S, W] int32
                # longest-agreeing-prefix acceptance (== Leviathan
                # rejection sampling for the deterministic drafter:
                # ys[j] ~ p_j via _sample, so draft j is accepted with
                # probability p_j(draft) and a rejection emits the
                # residual-distribution sample)
                agree = (ys[:, :Kd] == drafts) & (
                    jnp.arange(Kd, dtype=jnp.int32)[None, :]
                    < n_draft[:, None]
                )
                acc = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(
                    axis=1
                )  # [S] accepted draft tokens
                bonus = jnp.take_along_axis(ys, acc[:, None], axis=1)[:, 0]
                new_len = jnp.where(
                    sel, cache.length + acc + 1, cache.length
                )
                cache = new_cache._replace(length=new_len)
                tail = jnp.where(sel, bonus, tail)
                return ys, acc, cache, tail, rng

            self._verify_op = instrument_jit(
                f"llm.step_v{Wv}", _verify, model=self.label,
                metrics=metrics, donate_argnums=(1, 2),
            )

        # -- constrained program family (gofr_tpu.structured) -------------
        # Parallel variants of the chunk/step/verify programs that carry
        # the grammar machinery: gtab (the resident-grammar transition
        # table, read-only, retraced when its padded shape grows), gids
        # (per-slot grammar selector, shipped per dispatch — it only
        # changes at admission) and gstate (per-slot DFA state,
        # device-persistent and donated exactly like the chain tail, so
        # pipelined dispatches chain states without a host round trip).
        # FACTORIES only — nothing compiles until the first constrained
        # request admits (a constrained-free engine builds zero new
        # programs); the paged block below overrides them with the
        # pool-layout variants.
        #
        # MIRROR CONTRACT: each variant copies its plain factory's body
        # (same gather/scatter, pack/meta unpack, finish bookkeeping)
        # plus the grammar threading — the same deliberate duplication
        # the dense/paged pairs already carry, chosen over one factory
        # branching on every argument list and return tuple. A change to
        # step packing or scatter semantics in a plain factory MUST be
        # mirrored here (the cross-layout equality tests in
        # tests/test_structured.py are the tripwire).

        def _make_chunk_op_c(K: int):
            def _chunk_c(params, tokens, cache, active, temps, gstate,
                         gids, rng, gtab):
                sampler = (
                    lambda lg, tp, k, st: _g_sample(lg, tp, k, gtab, gids, st)
                )
                toks, last, cache, rng, gstate = chunk_fn(
                    params, cfg, tokens, cache, active, temps, rng,
                    n_steps=K, sample_fn=sampler, ring=self.kv.ring,
                    overlap=self._tp_gather, sample_state=gstate,
                )
                return toks, last, cache, gstate, rng

            return instrument_jit(
                f"llm.decode_chunk{K}g", _chunk_c, model=self.label,
                metrics=metrics, donate_argnums=(2, 5),
            )

        def _make_step_op_c(shape: int):
            K = decode_chunk

            def _step_c(params, cache, tail, active, temps, gstate,
                        pack, meta, gids, rng, gtab):
                """_step plus grammar threading. meta [4, nb] int32:
                slot | finish | grammar id | start DFA state — a row
                whose prompt completes this step samples its FIRST token
                masked by its start state (0 fresh; the host mirror's
                state for a preemption/failover continuation) and seeds
                the slot's device state; the fused decode chunk then
                advances every lane's state token-by-token."""
                tokens = pack[:, :shape]
                cursors = pack[:, shape]
                n_new = pack[:, shape + 1]
                req_temps = jax.lax.bitcast_convert_type(
                    pack[:, shape + 2], jnp.float32
                )
                slot_idx, finish = meta[0], meta[1]
                gid_row, gstart = meta[2], meta[3]
                aids_row = (
                    jnp.take(params["aids"], slot_idx, mode="clip")
                    if "aids" in params else None
                )
                sub = cache._replace(
                    k=jnp.take(cache.k, slot_idx, axis=1, mode="clip"),
                    v=jnp.take(cache.v, slot_idx, axis=1, mode="clip"),
                    length=cursors,
                )
                logits, sub = prefill_append(
                    params, cfg, tokens, sub, cursors, n_new,
                    ring=self.kv.ring, aids=aids_row,
                )
                cache = cache._replace(
                    k=cache.k.at[:, slot_idx].set(sub.k, mode="drop"),
                    v=cache.v.at[:, slot_idx].set(sub.v, mode="drop"),
                    length=cache.length.at[slot_idx].set(
                        cursors + n_new, mode="drop"
                    ),
                )
                rng, sub_rng = jax.random.split(rng)
                rows_g, on_r = _g_rows(gtab, gid_row, gstart)
                on_r = on_r & (finish == 1)
                first = _sample_raw(
                    _g_mask(logits, rows_g, on_r), req_temps, sub_rng
                )
                first = finite_guard(logits, first) if _numeric_check else first
                st1 = jnp.take_along_axis(
                    rows_g, jnp.clip(first, 0)[:, None], axis=1
                )[:, 0]
                fin_slot = jnp.where(finish == 1, slot_idx, _slots_oob)
                mid_slot = jnp.where(finish == 1, _slots_oob, slot_idx)
                active = active.at[mid_slot].set(False, mode="drop")
                tail = tail.at[fin_slot].set(first, mode="drop")
                active = active.at[fin_slot].set(True, mode="drop")
                temps = temps.at[fin_slot].set(req_temps, mode="drop")
                gstate = gstate.at[fin_slot].set(
                    jnp.where(on_r, st1, 0), mode="drop"
                )
                kept = logits if keep_logits else None
                sampler = (
                    lambda lg, tp, k, st: _g_sample(lg, tp, k, gtab, gids, st)
                )
                toks, last, cache, rng, gstate = chunk_fn(
                    params, cfg, tail, cache, active, temps, rng,
                    n_steps=K, sample_fn=sampler, ring=self.kv.ring,
                    overlap=self._tp_gather, sample_state=gstate,
                )
                return (
                    first, kept, toks, last, cache, active, temps, gstate, rng
                )

            return instrument_jit(
                f"llm.step_p{shape}_d{K}g", _step_c, model=self.label,
                metrics=metrics, donate_argnums=(1, 2, 3, 4, 5),
            )

        def _make_verify_op_c():
            from .models.transformer import verify_chunk as verify_fn_c

            Kd = self.spec_draft
            Wv = Kd + 1

            def _verify_c(params, cache, tail, temps, gstate, pack, gids,
                          rng, gtab):
                """Verify with per-position grammar masks: position j's
                context is tail + draft[:j], so its mask derives from the
                state reached by advancing the slot state through the
                DRAFT tokens (known at trace time — a tiny unrolled
                chain). An inadmissible draft token sends the chain state
                dead, but the masked sample at its own position is
                guaranteed to disagree with it, so acceptance always
                stops before a dead state can matter; the post-accept
                state advances from the accepted prefix's state by the
                bonus token."""
                drafts = pack[:, :Kd]
                n_draft = pack[:, Kd]
                sel = pack[:, Kd + 1] == 1
                n_in = jnp.where(sel, n_draft + 1, 0)
                toks = jnp.concatenate([tail[:, None], drafts], axis=1)
                logits, new_cache = verify_fn_c(
                    params, cfg, toks, cache, cache.length, n_in,
                    ring=self.kv.ring, aids=params.get("aids"),
                )
                rng, sub = jax.random.split(rng)
                keys = jax.random.split(sub, Wv)
                s = gstate
                states = [s]
                ys_list = []
                for j in range(Wv):
                    rows, on = _g_rows(gtab, gids, s)
                    yj = _sample_raw(
                        _g_mask(logits[:, j], rows, on), temps, keys[j]
                    )
                    yj = (
                        finite_guard(logits[:, j], yj)
                        if _numeric_check else yj
                    )
                    ys_list.append(yj)
                    if j < Kd:
                        nxt = jnp.take_along_axis(
                            rows, jnp.clip(drafts[:, j], 0)[:, None], axis=1
                        )[:, 0]
                        s = jnp.where(on, nxt, s)
                        states.append(s)
                ys = jnp.stack(ys_list, axis=1)  # [S, W] int32
                agree = (ys[:, :Kd] == drafts) & (
                    jnp.arange(Kd, dtype=jnp.int32)[None, :]
                    < n_draft[:, None]
                )
                acc = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)
                bonus = jnp.take_along_axis(ys, acc[:, None], axis=1)[:, 0]
                st_stack = jnp.stack(states, axis=1)  # [S, Wv]
                st_acc = jnp.take_along_axis(
                    st_stack, acc[:, None], axis=1
                )[:, 0]
                rows_a, on_a = _g_rows(gtab, gids, st_acc)
                nxt_a = jnp.take_along_axis(
                    rows_a, jnp.clip(bonus, 0)[:, None], axis=1
                )[:, 0]
                gstate = jnp.where(sel & on_a, nxt_a, gstate)
                new_len = jnp.where(sel, cache.length + acc + 1, cache.length)
                cache = new_cache._replace(length=new_len)
                tail = jnp.where(sel, bonus, tail)
                return ys, acc, cache, tail, gstate, rng

            return instrument_jit(
                f"llm.step_v{Wv}g", _verify_c, model=self.label,
                metrics=metrics, donate_argnums=(1, 2, 4),
            )

        self._mk_chunk_c = _make_chunk_op_c
        self._mk_step_c = _make_step_op_c
        self._mk_verify_c = _make_verify_op_c

        # -- paged-pool program family (kvcache.paged; docs/advanced-guide/
        # kv-cache.md). Same scheduler contracts as the contiguous family
        # above, but the slot KV lives in ONE block pool read/written
        # through per-slot block tables: decode attention goes through
        # ops.paged_chunk_decode_attention (Pallas paged kernel on TPU,
        # dense-gather fallback elsewhere), appends/verifies gather the
        # dense per-slot view at the program boundary and scatter exactly
        # the rows they wrote back through the table (write indices from
        # DEVICE lengths — rollback/pipeline safe). A host `live` mask
        # rides every decode-bearing program: the contiguous path could
        # afford clamped garbage writes for stale-active lanes, but a
        # paged stale lane's table may point at blocks that now belong to
        # someone else.
        if self.kv.paged:
            from .kvcache.paged import (
                copy_blocks, gather_slots, scatter_rows,
            )
            from .models.transformer import decode_chunk_paged
            from .ops import paged_kernel_ok

            Bp = self.kv.block
            _cap = self.kv.capacity
            _int8 = self.kv.int8
            _use_kernel = paged_kernel_ok(cfg.head_dim, Bp)

            def _sc(scales):
                return scales if _int8 else None

            def _gather_view(cache, scales, tables, lengths):
                sc = scales if _int8 else None
                return gather_slots(
                    cache.k, cache.v, tables, lengths,
                    scales=(None if sc is None else (sc[0], sc[1])),
                    dtype=cfg.dtype,
                )

            def _pool_scatter(cache, scales, tables, rows_k, rows_v, pos, valid):
                k2, v2, sc2 = scatter_rows(
                    cache.k, cache.v, tables, rows_k, rows_v, pos, valid,
                    scales=_sc(scales),
                )
                return cache._replace(k=k2, v=v2), (sc2 if _int8 else scales)

            def _rows_at(stack, pos):
                """[L, S, C, hkv, hd] rows at per-slot positions [S, W]."""
                idx = jnp.clip(pos, 0, stack.shape[2] - 1)
                return jnp.take_along_axis(
                    stack, idx[None, :, :, None, None], axis=2
                )

            def _make_paged_chunk_op(K: int):
                def _chunk(params, tail, cache, scales, tables, live, active, temps, rng):
                    eff = jnp.logical_and(active, live)
                    if _use_kernel:
                        toks, last, cache, sc_out, rng = decode_chunk_paged(
                            params, cfg, tail, cache, (scales if _int8 else None),
                            tables, eff, temps, rng,
                            n_steps=K, sample_fn=_sample, block=Bp,
                            overlap=self._tp_gather,
                        )
                        return toks, last, cache, (
                            sc_out if _int8 else scales
                        ), rng
                    dense = _gather_view(cache, scales, tables, cache.length)
                    toks, last, nd, rng = chunk_fn(
                        params, cfg, tail, dense, eff, temps, rng,
                        n_steps=K, sample_fn=_sample, ring=0,
                        overlap=self._tp_gather,
                    )
                    pos = cache.length[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :]
                    valid = eff[:, None] & (pos < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tables,
                        _rows_at(nd.k, pos), _rows_at(nd.v, pos), pos, valid,
                    )
                    return toks, last, cache._replace(length=nd.length), scales, rng

                return instrument_jit(
                    f"llm.decode_chunk{K}", _chunk, model=self.label,
                    metrics=metrics,
                    donate_argnums=((2, 3) if _int8 else (2,)),
                )

            self._chunk_ops = {decode_chunk: _make_paged_chunk_op(decode_chunk)}
            if self._chunk_short != decode_chunk:
                self._chunk_ops[self._chunk_short] = _make_paged_chunk_op(
                    self._chunk_short
                )

            def _insert_paged(cache, scales, new_cache, meta, tables):
                """Wave-admission insert: scatter each prefilled row's
                valid prefix through its slot's block table and set the
                device lengths. meta [2, M]: slot | row (pads repeat
                entry 0 — duplicate writes carry identical values)."""
                slot_idx, rowsel = meta[0], meta[1]
                tsub = jnp.take(
                    tables, jnp.clip(slot_idx, 0, slots - 1), axis=0
                )  # [M, MB]
                nk = jnp.take(new_cache.k, rowsel, axis=1)  # [L, M, W, ...]
                nv = jnp.take(new_cache.v, rowsel, axis=1)
                lens = jnp.take(new_cache.length, rowsel, axis=0)  # [M]
                W = nk.shape[2]
                pos = jnp.broadcast_to(
                    jnp.arange(W, dtype=jnp.int32)[None, :],
                    (slot_idx.shape[0], W),
                )
                valid = pos < jnp.minimum(lens, _cap)[:, None]
                cache, scales = _pool_scatter(
                    cache, scales, tsub, nk, nv, pos, valid
                )
                length = cache.length.at[slot_idx].set(lens, mode="drop")
                return cache._replace(length=length), scales

            self._insert_paged_op = instrument_jit(
                "llm.insert_many", _insert_paged, model=self.label,
                metrics=metrics, donate_argnums=((0, 1) if _int8 else (0,)),
            )

            def _seed(cache, scales, srcs, dsts, slot_idx, seed_lens):
                """Exact-hit/session seeding: block-copy partial tails
                (srcs -> dsts; pad lanes dst >= NB are dropped) and set
                device lengths (pad lanes slot >= slots are dropped)."""
                k2, v2, sc2 = copy_blocks(
                    cache.k, cache.v, srcs, dsts, scales=_sc(scales)
                )
                length = cache.length.at[slot_idx].set(seed_lens, mode="drop")
                return (
                    cache._replace(k=k2, v=v2, length=length),
                    (sc2 if _int8 else scales),
                )

            self._seed_op = instrument_jit(
                "llm.kv_seed", _seed, model=self.label, metrics=metrics,
                donate_argnums=((0, 1) if _int8 else (0,)),
            )

            def _restore(cache, scales, hk, hv, hs, dsts):
                """Session restore: host-fetched blocks land back in the
                pool at freshly-allocated ids (byte-identical h2d)."""
                k2 = cache.k.at[:, dsts].set(hk, mode="drop")
                v2 = cache.v.at[:, dsts].set(hv, mode="drop")
                if _int8:
                    scales = scales.at[:, :, dsts].set(hs, mode="drop")
                return cache._replace(k=k2, v=v2), scales

            self._restore_base = _restore
            self._restore_ops: dict[int, Any] = {}

            def _make_paged_step_op(shape: int):
                K = decode_chunk

                def _step(params, cache, scales, tables, live, tail, active,
                          temps, pack, meta, rng):
                    tokens = pack[:, :shape]
                    cursors = pack[:, shape]
                    n_new = pack[:, shape + 1]
                    req_temps = jax.lax.bitcast_convert_type(
                        pack[:, shape + 2], jnp.float32
                    )
                    slot_idx, finish = meta[0], meta[1]
                    aids_row = (
                        jnp.take(params["aids"], slot_idx, mode="clip")
                        if "aids" in params else None
                    )
                    tsub = jnp.take(
                        tables, jnp.clip(slot_idx, 0, slots - 1), axis=0
                    )
                    sub = _gather_view(cache, scales, tsub, cursors)
                    logits, sub2 = prefill_append(
                        params, cfg, tokens, sub, cursors, n_new, ring=0,
                        aids=aids_row,
                    )
                    c = shape
                    pos_a = cursors[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
                    valid_a = (
                        jnp.arange(c, dtype=jnp.int32)[None, :] < n_new[:, None]
                    ) & (pos_a < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tsub,
                        _rows_at(sub2.k, pos_a), _rows_at(sub2.v, pos_a),
                        pos_a, valid_a,
                    )
                    length = cache.length.at[slot_idx].set(
                        cursors + n_new, mode="drop"
                    )
                    cache = cache._replace(length=length)
                    rng, sub_rng = jax.random.split(rng)
                    first = _sample(logits, req_temps, sub_rng)
                    fin_slot = jnp.where(finish == 1, slot_idx, _slots_oob)
                    mid_slot = jnp.where(finish == 1, _slots_oob, slot_idx)
                    active = active.at[mid_slot].set(False, mode="drop")
                    tail = tail.at[fin_slot].set(first, mode="drop")
                    active = active.at[fin_slot].set(True, mode="drop")
                    temps = temps.at[fin_slot].set(req_temps, mode="drop")
                    kept = logits if keep_logits else None
                    eff = jnp.logical_and(active, live)
                    if _use_kernel:
                        toks, last, cache, sc, rng = decode_chunk_paged(
                            params, cfg, tail, cache, (scales if _int8 else None),
                            tables, eff, temps, rng,
                            n_steps=K, sample_fn=_sample, block=Bp,
                            overlap=self._tp_gather,
                        )
                        scales = sc if _int8 else scales
                    else:
                        dense = _gather_view(cache, scales, tables, cache.length)
                        toks, last, nd, rng = chunk_fn(
                            params, cfg, tail, dense, eff, temps, rng,
                            n_steps=K, sample_fn=_sample, ring=0,
                            overlap=self._tp_gather,
                        )
                        pos = cache.length[:, None] + jnp.arange(
                            K, dtype=jnp.int32
                        )[None, :]
                        valid = eff[:, None] & (pos < _cap)
                        cache, scales = _pool_scatter(
                            cache, scales, tables,
                            _rows_at(nd.k, pos), _rows_at(nd.v, pos), pos, valid,
                        )
                        cache = cache._replace(length=nd.length)
                    return first, kept, toks, last, cache, scales, active, temps, rng

                name = f"llm.step_p{shape}_d{K}"
                return instrument_jit(
                    name, _step, model=self.label, metrics=metrics,
                    donate_argnums=((1, 2, 6, 7) if _int8 else (1, 6, 7)),
                )

            if self.chunked:
                self._step_ops = {
                    shape: _make_paged_step_op(shape)
                    for shape in self.chunk_shapes
                }

            if self.speculative:
                from .models.transformer import verify_chunk as verify_fn

                Kd = self.spec_draft
                Wv = Kd + 1

                def _verify_paged(params, cache, scales, tables, tail, temps, pack, rng):
                    drafts = pack[:, :Kd]
                    n_draft = pack[:, Kd]
                    sel = pack[:, Kd + 1] == 1
                    n_in = jnp.where(sel, n_draft + 1, 0)
                    toks = jnp.concatenate([tail[:, None], drafts], axis=1)
                    dense = _gather_view(cache, scales, tables, cache.length)
                    logits, nd = verify_fn(
                        params, cfg, toks, dense, cache.length, n_in, ring=0,
                        aids=params.get("aids"),
                    )
                    pos = cache.length[:, None] + jnp.arange(
                        Wv, dtype=jnp.int32
                    )[None, :]
                    valid = (
                        jnp.arange(Wv, dtype=jnp.int32)[None, :] < n_in[:, None]
                    ) & (pos < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tables,
                        _rows_at(nd.k, pos), _rows_at(nd.v, pos), pos, valid,
                    )
                    rng, sub = jax.random.split(rng)
                    keys = jax.random.split(sub, Wv)
                    ys = jnp.stack(
                        [_sample(logits[:, j], temps, keys[j]) for j in range(Wv)],
                        axis=1,
                    )
                    agree = (ys[:, :Kd] == drafts) & (
                        jnp.arange(Kd, dtype=jnp.int32)[None, :]
                        < n_draft[:, None]
                    )
                    acc = jnp.cumprod(agree.astype(jnp.int32), axis=1).sum(axis=1)
                    bonus = jnp.take_along_axis(ys, acc[:, None], axis=1)[:, 0]
                    new_len = jnp.where(sel, cache.length + acc + 1, cache.length)
                    cache = cache._replace(length=new_len)
                    tail = jnp.where(sel, bonus, tail)
                    return ys, acc, cache, scales, tail, rng

                self._verify_op = instrument_jit(
                    f"llm.step_v{Wv}", _verify_paged, model=self.label,
                    metrics=metrics,
                    donate_argnums=((1, 2, 4) if _int8 else (1, 4)),
                )

            # constrained variants over the pool layout (same grammar
            # machinery as the dense factories above; lazily compiled)
            def _make_paged_chunk_op_c(K: int):
                def _chunk_c(params, tail, cache, scales, tables, live,
                             active, temps, gstate, gids, rng, gtab):
                    eff = jnp.logical_and(active, live)
                    sampler = (
                        lambda lg, tp, k, st:
                        _g_sample(lg, tp, k, gtab, gids, st)
                    )
                    if _use_kernel:
                        toks, last, cache, sc_out, rng, gstate = (
                            decode_chunk_paged(
                                params, cfg, tail, cache,
                                (scales if _int8 else None),
                                tables, eff, temps, rng,
                                n_steps=K, sample_fn=sampler, block=Bp,
                                overlap=self._tp_gather, sample_state=gstate,
                            )
                        )
                        return toks, last, cache, (
                            sc_out if _int8 else scales
                        ), gstate, rng
                    dense = _gather_view(cache, scales, tables, cache.length)
                    toks, last, nd, rng, gstate = chunk_fn(
                        params, cfg, tail, dense, eff, temps, rng,
                        n_steps=K, sample_fn=sampler, ring=0,
                        overlap=self._tp_gather, sample_state=gstate,
                    )
                    pos = cache.length[:, None] + jnp.arange(
                        K, dtype=jnp.int32
                    )[None, :]
                    valid = eff[:, None] & (pos < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tables,
                        _rows_at(nd.k, pos), _rows_at(nd.v, pos), pos, valid,
                    )
                    return (
                        toks, last, cache._replace(length=nd.length),
                        scales, gstate, rng,
                    )

                return instrument_jit(
                    f"llm.decode_chunk{K}g", _chunk_c, model=self.label,
                    metrics=metrics,
                    donate_argnums=((2, 3, 8) if _int8 else (2, 8)),
                )

            def _make_paged_step_op_c(shape: int):
                K = decode_chunk

                def _step_c(params, cache, scales, tables, live, tail,
                            active, temps, gstate, pack, meta, gids, rng,
                            gtab):
                    tokens = pack[:, :shape]
                    cursors = pack[:, shape]
                    n_new = pack[:, shape + 1]
                    req_temps = jax.lax.bitcast_convert_type(
                        pack[:, shape + 2], jnp.float32
                    )
                    slot_idx, finish = meta[0], meta[1]
                    gid_row, gstart = meta[2], meta[3]
                    aids_row = (
                        jnp.take(params["aids"], slot_idx, mode="clip")
                        if "aids" in params else None
                    )
                    tsub = jnp.take(
                        tables, jnp.clip(slot_idx, 0, slots - 1), axis=0
                    )
                    sub = _gather_view(cache, scales, tsub, cursors)
                    logits, sub2 = prefill_append(
                        params, cfg, tokens, sub, cursors, n_new, ring=0,
                        aids=aids_row,
                    )
                    c = shape
                    pos_a = cursors[:, None] + jnp.arange(
                        c, dtype=jnp.int32
                    )[None, :]
                    valid_a = (
                        jnp.arange(c, dtype=jnp.int32)[None, :]
                        < n_new[:, None]
                    ) & (pos_a < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tsub,
                        _rows_at(sub2.k, pos_a), _rows_at(sub2.v, pos_a),
                        pos_a, valid_a,
                    )
                    length = cache.length.at[slot_idx].set(
                        cursors + n_new, mode="drop"
                    )
                    cache = cache._replace(length=length)
                    rng, sub_rng = jax.random.split(rng)
                    rows_g, on_r = _g_rows(gtab, gid_row, gstart)
                    on_r = on_r & (finish == 1)
                    first = _sample_raw(
                        _g_mask(logits, rows_g, on_r), req_temps, sub_rng
                    )
                    first = (
                        finite_guard(logits, first)
                        if _numeric_check else first
                    )
                    st1 = jnp.take_along_axis(
                        rows_g, jnp.clip(first, 0)[:, None], axis=1
                    )[:, 0]
                    fin_slot = jnp.where(finish == 1, slot_idx, _slots_oob)
                    mid_slot = jnp.where(finish == 1, _slots_oob, slot_idx)
                    active = active.at[mid_slot].set(False, mode="drop")
                    tail = tail.at[fin_slot].set(first, mode="drop")
                    active = active.at[fin_slot].set(True, mode="drop")
                    temps = temps.at[fin_slot].set(req_temps, mode="drop")
                    gstate = gstate.at[fin_slot].set(
                        jnp.where(on_r, st1, 0), mode="drop"
                    )
                    kept = logits if keep_logits else None
                    eff = jnp.logical_and(active, live)
                    sampler = (
                        lambda lg, tp, k, st:
                        _g_sample(lg, tp, k, gtab, gids, st)
                    )
                    if _use_kernel:
                        toks, last, cache, sc, rng, gstate = (
                            decode_chunk_paged(
                                params, cfg, tail, cache,
                                (scales if _int8 else None),
                                tables, eff, temps, rng,
                                n_steps=K, sample_fn=sampler, block=Bp,
                                overlap=self._tp_gather, sample_state=gstate,
                            )
                        )
                        scales = sc if _int8 else scales
                    else:
                        dense = _gather_view(
                            cache, scales, tables, cache.length
                        )
                        toks, last, nd, rng, gstate = chunk_fn(
                            params, cfg, tail, dense, eff, temps, rng,
                            n_steps=K, sample_fn=sampler, ring=0,
                            overlap=self._tp_gather, sample_state=gstate,
                        )
                        pos = cache.length[:, None] + jnp.arange(
                            K, dtype=jnp.int32
                        )[None, :]
                        valid = eff[:, None] & (pos < _cap)
                        cache, scales = _pool_scatter(
                            cache, scales, tables,
                            _rows_at(nd.k, pos), _rows_at(nd.v, pos),
                            pos, valid,
                        )
                        cache = cache._replace(length=nd.length)
                    return (
                        first, kept, toks, last, cache, scales, active,
                        temps, gstate, rng,
                    )

                return instrument_jit(
                    f"llm.step_p{shape}_d{K}g", _step_c, model=self.label,
                    metrics=metrics,
                    donate_argnums=(
                        (1, 2, 6, 7, 8) if _int8 else (1, 6, 7, 8)
                    ),
                )

            def _make_paged_verify_op_c():
                from .models.transformer import verify_chunk as verify_fn_c

                Kd = self.spec_draft
                Wv = Kd + 1

                def _verify_c(params, cache, scales, tables, tail, temps,
                              gstate, pack, gids, rng, gtab):
                    drafts = pack[:, :Kd]
                    n_draft = pack[:, Kd]
                    sel = pack[:, Kd + 1] == 1
                    n_in = jnp.where(sel, n_draft + 1, 0)
                    toks = jnp.concatenate([tail[:, None], drafts], axis=1)
                    dense = _gather_view(cache, scales, tables, cache.length)
                    logits, nd = verify_fn_c(
                        params, cfg, toks, dense, cache.length, n_in, ring=0,
                        aids=params.get("aids"),
                    )
                    pos = cache.length[:, None] + jnp.arange(
                        Wv, dtype=jnp.int32
                    )[None, :]
                    valid = (
                        jnp.arange(Wv, dtype=jnp.int32)[None, :]
                        < n_in[:, None]
                    ) & (pos < _cap)
                    cache, scales = _pool_scatter(
                        cache, scales, tables,
                        _rows_at(nd.k, pos), _rows_at(nd.v, pos), pos, valid,
                    )
                    rng, sub = jax.random.split(rng)
                    keys = jax.random.split(sub, Wv)
                    s = gstate
                    states = [s]
                    ys_list = []
                    for j in range(Wv):
                        rows, on = _g_rows(gtab, gids, s)
                        yj = _sample_raw(
                            _g_mask(logits[:, j], rows, on), temps, keys[j]
                        )
                        yj = (
                            finite_guard(logits[:, j], yj)
                            if _numeric_check else yj
                        )
                        ys_list.append(yj)
                        if j < Kd:
                            nxt = jnp.take_along_axis(
                                rows, jnp.clip(drafts[:, j], 0)[:, None],
                                axis=1,
                            )[:, 0]
                            s = jnp.where(on, nxt, s)
                            states.append(s)
                    ys = jnp.stack(ys_list, axis=1)
                    agree = (ys[:, :Kd] == drafts) & (
                        jnp.arange(Kd, dtype=jnp.int32)[None, :]
                        < n_draft[:, None]
                    )
                    acc = jnp.cumprod(
                        agree.astype(jnp.int32), axis=1
                    ).sum(axis=1)
                    bonus = jnp.take_along_axis(ys, acc[:, None], axis=1)[:, 0]
                    st_stack = jnp.stack(states, axis=1)
                    st_acc = jnp.take_along_axis(
                        st_stack, acc[:, None], axis=1
                    )[:, 0]
                    rows_a, on_a = _g_rows(gtab, gids, st_acc)
                    nxt_a = jnp.take_along_axis(
                        rows_a, jnp.clip(bonus, 0)[:, None], axis=1
                    )[:, 0]
                    gstate = jnp.where(sel & on_a, nxt_a, gstate)
                    new_len = jnp.where(
                        sel, cache.length + acc + 1, cache.length
                    )
                    cache = cache._replace(length=new_len)
                    tail = jnp.where(sel, bonus, tail)
                    return ys, acc, cache, scales, tail, gstate, rng

                return instrument_jit(
                    f"llm.step_v{Wv}g", _verify_c, model=self.label,
                    metrics=metrics,
                    donate_argnums=((1, 2, 4, 6) if _int8 else (1, 4, 6)),
                )

            self._mk_chunk_c = _make_paged_chunk_op_c
            self._mk_step_c = _make_paged_step_op_c
            self._mk_verify_c = _make_paged_verify_op_c
        self._rng = jax.random.PRNGKey(0)

        if self.kv.paged:
            # ONE block pool backs every slot; per-slot block tables map
            # logical rows to pool rows. self.cache keeps the KVCache
            # shape contract (k/v/length) so the donation chains and
            # state threading below are identical to the contiguous
            # layout — only the k/v geometry differs.
            self.cache, self._kv_scales = self.kv.pool_arrays(jnp)
            if self._kv_scales is None:
                self._kv_scales = jnp.zeros((0,), jnp.float32)
            self._tables_dev = jnp.zeros(
                (slots, self.kv.table_width), jnp.int32
            )
            if device is not None:
                self.cache = jax.device_put(self.cache, device)
                self._kv_scales = jax.device_put(self._kv_scales, device)
                self._tables_dev = jax.device_put(self._tables_dev, device)
        else:
            self._kv_scales = None
            self._tables_dev = None
            self.cache = self.kv.init_cache(slots)
            if device is not None:
                self.cache = jax.device_put(self.cache, device)
        self._kv_sharding = None
        if self._sharded:
            # KV sharded along heads where the model allows, replicated
            # under MQA (parallel.sharding.kv_specs) — committed once
            # here; donation keeps the layout through every step/chunk/
            # verify program, so the pool never silently migrates to one
            # chip of the submesh.
            from jax.sharding import NamedSharding

            from .parallel.sharding import kv_specs

            self._kv_sharding = NamedSharding(
                mesh, kv_specs(cfg, mesh, paged=self.kv.paged)
            )
            self.cache = self.cache._replace(
                k=jax.device_put(self.cache.k, self._kv_sharding),
                v=jax.device_put(self.cache.v, self._kv_sharding),
            )
        # host-side upper bound on each slot's device length (paged block
        # allocation watermark; conservative under speculative pipelining)
        self._kv_hi = [0] * slots
        # end-of-turn session publishes deferred from the collector to the
        # scheduler thread (the only thread allowed to dispatch device
        # work against the donated pool): (slot, request) pairs
        self._session_pub: deque = deque()
        # host-work closures other threads queue for the SCHEDULER thread
        # (KV handoff export/import dispatch against the donated pool):
        # (fn, box) pairs — box carries done-event/result/error back
        self._sched_work: deque = deque()
        self._slot_req: list[GenRequest | None] = [None] * slots
        # device-resident batch state: chain tail, active mask, temps.
        # active is never cleared on retire (a stale True only advances a
        # garbage cursor in an unowned slot, clamped in-bounds) — clearing
        # would cost a host->device transfer per completion.
        self._tail = jnp.zeros((slots,), jnp.int32)
        self._active = jnp.zeros((slots,), bool)
        self._temps = jnp.zeros((slots,), jnp.float32)
        # -- grammar-constrained decoding (gofr_tpu.structured;
        # docs/advanced-guide/structured-decoding.md) ---------------------
        # Per-slot DFA state lives on device like the chain tail (the
        # fused chunk advances it token-by-token, and pipelined
        # dispatches must chain it without a host fetch); the resident
        # grammar table and the per-slot grammar ids are host-owned.
        # Chunked scheduler only: the wave path samples first tokens in
        # programs the mask does not ride.
        if constrained is None:
            constrained = _os.environ.get("TPU_LLM_CONSTRAINED", "1") != "0"
        self.constrained = bool(constrained) and self.chunked
        if constrained_grammars is None:
            constrained_grammars = int(
                _os.environ.get("TPU_LLM_CONSTRAINED_GRAMMARS", "8") or 8
            )
        self._g_cap = max(1, int(constrained_grammars))
        self._grammars: list[Any] = []  # resident TokenGrammars (index=gid)
        self._g_refs: list[int] = []  # live requests holding each gid
        self._gr_dev = None  # padded [G, Smax, V] device transition table
        self._gstate = jnp.zeros((slots,), jnp.int32)
        self.constrained_requests = 0  # lifetime constrained submissions
        self.spec_proposed_c = 0  # spec drafts proposed for constrained lanes
        self.spec_accepted_c = 0  # spec drafts accepted for constrained lanes
        self._chunk_ops_c: dict[int, Any] = {}  # built on first use
        self._step_ops_c: dict[int, Any] = {}
        self._verify_op_c = None
        self.adapter_requests = 0  # lifetime adapter-attributed submissions
        if device is not None:
            (
                self._tail, self._active, self._temps, self._gstate,
                self._rng,
            ) = jax.device_put(
                (
                    self._tail, self._active, self._temps, self._gstate,
                    self._rng,
                ),
                device,
            )
        self._admit_q: queue.Queue[GenRequest | None] = queue.Queue()
        self._waiting: list[GenRequest] = []  # drained queue, scheduler-only
        self.submitted = 0  # total requests ever submitted (router telemetry)
        self._admitting = 0  # sliced out of _waiting, not yet slotted
        # dispatch telemetry (cheap counters; exposed via stats() so a
        # saturation run reveals occupancy and wave-size efficiency)
        self._stat_chunks = 0  # decode chunks dispatched
        self._stat_chunk_steps = 0  # decode steps dispatched
        self._stat_active_sum = 0  # sum of active slots at chunk dispatch
        self._stat_waves: dict[int, int] = {}  # prefill wave width -> count
        self._stat_wave_reqs = 0  # requests admitted via waves
        self._stat_steps = 0  # unified steps dispatched (chunked scheduler)
        self._stat_step_tokens = 0  # tokens packed into unified steps
        # speculative-decoding telemetry (gofr_tpu.spec)
        self.spec_steps = 0  # verify dispatches
        self.spec_proposed = 0  # draft tokens proposed
        self.spec_accepted = 0  # draft tokens accepted
        self.spec_plain = 0  # verify lanes run with zero draft (plain decode)
        self._spec_hold = 0  # plain-chunk burst left before the next probe
        self._spec_rr = 0  # budget-cut rotation cursor (verify slot fairness)
        self._prefilling: deque[GenRequest] = deque()  # resident, not decoding
        self._load_tokens = 0  # outstanding token estimate (router weighting)
        self._last_submit_t: float | None = None
        self._ema_gap: float | None = None  # EMA inter-arrival (rate estimate)
        self._stop = False
        # in-flight device work, oldest first. Entries snapshot the REQUEST
        # objects they serve, so a slot can be reassigned while older
        # chunks still carry its previous request's tokens:
        #   ("chunk", toks_dev [K,S], [req-or-None per slot])
        #   ("prefill", first_dev [nb], [(slot, req), ...])
        self._inflight: deque = deque()
        # Two engine threads: the SCHEDULER owns every device dispatch
        # (admission prefills, inserts, decode chunks); the COLLECTOR owns
        # the blocking device->host fetches (~95 ms RTT each through the
        # axon tunnel) and token emission. One thread doing both stalls
        # dispatch behind every fetch and leaves the device idle.
        self._lock = threading.RLock()
        self._work_cv = threading.Condition(self._lock)  # inflight appended
        self._kick = threading.Event()  # scheduler wake: submit/slots freed
        self._processing: tuple | None = None  # entry popped, not yet emitted
        self._jumped = False  # prefill-priority ration (one per chunk)
        self._fetch_fail_streak = 0  # consecutive collector fetch failures
        self._jnp = jnp
        self._jax = jax

        if warmup:
            self._warm()
        self._thread = threading.Thread(
            target=self._schedule_loop, name="llm-engine-sched", daemon=True
        )
        self._collector = threading.Thread(
            target=self._collect_loop, name="llm-engine-collect", daemon=True
        )
        self._thread.start()
        self._collector.start()
        if self.step_watchdog_s > 0:
            from .resilience import StepWatchdog

            # started AFTER _warm: beats wrap serving dispatch/fetch only,
            # so cold compiles can never trip a seconds-scale threshold
            self.watchdog = StepWatchdog(self, self.step_watchdog_s)

    # -- public API -------------------------------------------------------
    def submit(self, req: GenRequest) -> GenRequest:
        if self._stop:
            raise EngineStoppedError("engine stopped")
        if self._draining:
            raise EngineDraining("engine draining (rolling deploy)")
        plen = len(req.prompt_tokens)
        if plen >= self.max_seq_len:
            raise ValueError(
                f"prompt of {plen} tokens exceeds max_seq_len {self.max_seq_len}"
            )
        # Cap max_new_tokens so the slot's cursor can never clamp-overwrite
        # its own live rows: while a request is incomplete its length stays
        # <= prompt + max_new + chunk (chunk-granularity rounding), and the
        # end-of-chunk merge needs a further chunk of slack. A request that
        # cannot emit a single token is rejected outright.
        room = self.max_seq_len - plen - 2 * self.decode_chunk
        if room < 1:
            raise ValueError(
                f"prompt of {plen} tokens leaves no decode room at "
                f"max_seq_len {self.max_seq_len} (chunk {self.decode_chunk})"
            )
        # emitted discounts work already done — a failover continuation
        # re-submits with its history folded into the prompt, and only
        # the REMAINING tokens need decode room (emitted == 0 for fresh
        # requests, so this is the original cap there)
        if req.max_new_tokens - req.emitted > room:
            req.max_new_tokens = room + req.emitted
            req.capped = True
        if self.kv.paged:
            # a request whose worst case exceeds the WHOLE pool could
            # never be admitted — reject now instead of queueing forever
            # (pool-pressure queueing is for requests that fit eventually)
            need = self.kv.blocks_for(
                self.kv.reserve_tokens(plen, req.max_new_tokens)
            )
            if need > self.kv.pool.n_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks, pool holds "
                    f"{self.kv.pool.n_blocks} (raise kv_pool_blocks / "
                    "TPU_LLM_KV_POOL_BLOCKS)"
                )
        # -- grammar-constrained decoding (gofr_tpu.structured;
        # docs/advanced-guide/structured-decoding.md) ---------------------
        if req.grammar is not None:
            if not self.constrained:
                raise ValueError(
                    "grammar-constrained decoding requires the chunked "
                    "scheduler (step_token_budget > 0) and "
                    "TPU_LLM_CONSTRAINED=1"
                )
            g = req.grammar
            if req.eos_token < 0:
                # the grammar's completion transition IS the eos: without
                # it the stream would run past the closed value into
                # dead-state garbage
                req.eos_token = g.eos_id
            elif req.eos_token != g.eos_id:
                raise ValueError(
                    f"request eos_token {req.eos_token} != grammar eos "
                    f"{g.eos_id} (the grammar closes the stream)"
                )
        # -- overload control (docs/advanced-guide/overload.md) -----------
        # Anything except the literal "batch" is interactive: the edges
        # forward the X-GoFr-Priority header verbatim, and a typo must
        # degrade to the latency-safe class, not an error.
        req.priority = "batch" if req.priority == "batch" else "interactive"
        # -- per-tenant token-rate quota (gofr_tpu.goodput) ---------------
        # Hard admission ceiling on the MEASURED usage window (chargeback
        # truth, not fair-share weights): tenants without an explicit
        # quota fall through to fair-share only. Probes are exempt — an
        # over-quota tenant must not block the canary that protects it.
        # Checked before any reference is taken (grammar/adapter) so a
        # quota shed never leaks engine state.
        if self.quota is not None and self.quota.active() and not req.probe:
            tenant = req.client or (
                f"adapter:{req.adapter}" if req.adapter else "-"
            )
            quota_retry = self.quota.check(tenant)
            if quota_retry is not None:
                self.quota_sheds += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_llm_quota_sheds_total",
                        model=self.label, tenant=tenant,
                    )
                raise EngineOverloaded(
                    f"tenant {tenant!r} over token-rate quota "
                    f"{self.quota.quota_for(tenant):.0f} tok/s "
                    "(TPU_LLM_TENANT_QUOTA_TOK_S)",
                    retry_after=quota_retry,
                )
        wait_s = self.predicted_wait_s()
        spec = self.faults.take("overload_pressure", self.label)
        if spec is not None:
            # chaos seam: this submit sees `delay` seconds of predicted
            # wait regardless of the real backlog (deterministic
            # brownout/shed in tier-1 and the CI overload smoke)
            self._count_fault("overload_pressure")
            wait_s = spec.delay if spec.delay > 0 else 3600.0
        self.overload.observe(wait_s)
        shed_after = self.overload.should_shed(wait_s)
        if shed_after is not None:
            # predicted-wait shed: reject EARLY, before max_queue, with
            # the time the backlog needs to drain — a client told WHEN to
            # come back offers its load where capacity will exist
            self.sheds_predicted += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_sheds_predicted_total", model=self.label
                )
            raise EngineOverloaded(
                f"predicted queue wait {wait_s:.1f}s exceeds shed "
                f"threshold {self.overload.shed_wait_s:.1f}s",
                retry_after=shed_after,
            )
        # brownout degrade: clamp bounds the REMAINING tokens — a
        # failover/preemption continuation re-submits with emitted > 0
        # and must not land below what it already streamed
        clamp = self.overload.clamp(
            req.max_new_tokens - req.emitted, req.priority
        ) + req.emitted
        if clamp < req.max_new_tokens:
            req.max_new_tokens = clamp
            req.browned = True
            self.brownout_clamped += 1
        if self.max_queue is not None:
            depth = self._admit_q.qsize() + len(self._waiting) + self._admitting
            if depth >= self.max_queue:
                self.rejected += 1
                raise EngineOverloaded(
                    f"admission queue full ({depth} >= {self.max_queue})",
                    retry_after=wait_s if wait_s else 1.0,
                )
        if req.grammar is not None:
            # register AFTER every shed/reject path: a rejected submit
            # must not leak a resident-grammar reference. Registration
            # wall (dedup hit or table compile+ship) is the mask-prep
            # cost the app_llm_constrained_mask_seconds series tracks.
            t0g = time.perf_counter()
            with self._lock:
                req._g_id = self._register_grammar(req.grammar)
                self._g_refs[req._g_id] += 1
            self.constrained_requests += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_constrained_requests_total", model=self.label
                )
                self.metrics.record_histogram(
                    "app_llm_constrained_mask_seconds",
                    time.perf_counter() - t0g, model=self.label,
                )
        if req.adapter:
            # acquire AFTER every shed/reject path (same discipline as
            # grammar registration above): a rejected submit must not
            # leak a pool reference. Re-resolve unconditionally — a
            # failover continuation arrives with a stale _aid from a
            # replica whose pool bound the name to a different gid.
            if not self.lora_slots:
                raise ValueError(
                    f"request names adapter {req.adapter!r} but this "
                    "engine has no adapter pool (lora_slots=0; set "
                    "TPU_LLM_LORA_SLOTS)"
                )
            with self._lock:
                try:
                    req._aid = self._lora_pool.acquire(req.adapter)
                except KeyError:
                    raise UnknownAdapterError(
                        req.adapter, self._lora_pool.resident()
                    ) from None
            # default billing identity: un-attributed tenant traffic
            # bills to the adapter's pseudo-client so per-adapter quotas
            # (ledger.set_weight at register time) take effect without
            # every caller threading a client id
            if not req.client:
                req.client = f"adapter:{req.adapter}"
            self.adapter_requests += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_adapter_requests_total", model=self.label,
                    adapter=req.adapter,
                )
        else:
            req._aid = 0
        now = time.perf_counter()
        req.submitted_at = now
        req.phase = "queued"
        # version stamp: once this request has emitted a token, failover
        # re-dispatch pins to this model version (no mixed-version stream)
        req.engine_version = self.version
        # continuations (failover re-submits) carry engine-side spec
        # state from their previous replica; it is meaningless here
        req._spec_pending = []
        req._spec_inflight = 0
        if self.tracer is not None and req.span is None:
            # span is None except for failover continuations, whose
            # llm.request span from the original submit stays open across
            # replicas — a second start would orphan the first
            # Contextvar capture happens HERE, on the submitting thread —
            # the scheduler/collector threads that serve the request never
            # see the caller's context, so every later phase span is
            # parented through the ids captured now. An EXPLICIT
            # traceparent on the request outranks the contextvar: it is a
            # deliberate re-parent by infrastructure code (the disagg
            # journey span, batch workers, failover seams) that may run
            # on a thread where someone else's span is still live.
            from .tracing import current_span, parse_traceparent

            link = parse_traceparent(req.traceparent)
            if link is None:
                parent = current_span()
                if parent is not None and parent.end_ns == 0:
                    link = (parent.trace_id, parent.span_id)
            req.span = self.tracer.start_detached_span(
                "llm.request", parent=link,
                attributes={
                    "llm.model": self.label,
                    "llm.request_id": req.id,
                    "llm.prompt_tokens": plen,
                    "llm.max_new_tokens": req.max_new_tokens,
                },
            )
            if req.journey_id is None:
                req.journey_id = req.span.trace_id
        elif self.tracer is not None and (req.deaths or req.retries or req.preempted):
            # failover continuation landing on a new replica: the original
            # llm.request span stays open (same trace — the journey_id is
            # stable across kills), and this hop gets its own continuation
            # span LINKED to the original so a 3-hop failover reads as one
            # journey even in link-aware external backends.
            req.hop += 1
            t_ns = time.time_ns()
            self.tracer.record_span(
                "llm.continuation",
                trace_id=req.span.trace_id,
                parent_id=req.span.span_id,
                start_ns=t_ns, end_ns=t_ns,
                attributes={
                    "llm.model": self.label,
                    "llm.request_id": req.id,
                    "llm.hop": req.hop,
                    "llm.kind": "failover",
                    "llm.deaths": req.deaths,
                    "llm.preempted": req.preempted,
                    "llm.emitted": req.emitted,
                },
                links=[(req.span.trace_id, req.span.span_id)],
            )
        if req.journey_id is None and req.span is not None:
            req.journey_id = req.span.trace_id
        self.submitted += 1  # routing/diagnostic counter (GIL-atomic enough)
        with self._lock:
            # outstanding-token estimate for the replica router: prompt
            # remainder + expected REMAINING decode, credited back as
            # chunks append and tokens emit (load_tokens()). max_new
            # minus emitted, not max_new: a failover continuation
            # re-submits with emitted > 0, and billing the already-
            # emitted tokens again would overweight the replica for work
            # nobody will do — multi-token speculative spans make that
            # drift material (docs/advanced-guide/speculative-decoding.md)
            req._load_acct = plen + max(0, req.max_new_tokens - req.emitted)
            self._load_tokens += req._load_acct
            # EMA update under the lock: concurrent submitters racing the
            # read-modify-write could blend NEGATIVE gaps into the estimate
            # and spuriously hold low-rate traffic for admit_delay
            last, self._last_submit_t = self._last_submit_t, now
            if last is not None:
                gap = min(max(now - last, 0.0), 1.0)
                self._ema_gap = (
                    gap if self._ema_gap is None else 0.8 * self._ema_gap + 0.2 * gap
                )
        if self.ledger is not None:
            # new-arrival lift BEFORE the request becomes orderable: a
            # client returning from idle starts at the active floor, not
            # at whatever stale credit its old counter banked
            self.ledger.touch(req.client)
        # flight record: capture the re-execution inputs NOW, so an
        # in-flight request is already replayable when the engine dies
        # (a failover continuation re-records its continuation prompt)
        self.flightrec.start(req, self)
        self._admit_q.put(req)
        # TOCTOU with _die()/close(): if the engine stopped between the
        # _stop check above and this put, its one-shot drain may already
        # have run and nothing will ever read the queue again — drain it
        # ourselves so the request cannot hang until stream timeout
        if self._stop:
            self._drain_pending()
        self._kick.set()
        return req

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def stats(self) -> dict:
        with self._lock:
            return {
                "version": self.version,
                "tp_degree": self.tp_degree,
                "tp_overlap": self.tp_overlap,
                "role": self.role,
                "disconnect_cancels": self.disconnect_cancels,
                "errored": self.errored,
                "slots": self.slots,
                "active": sum(r is not None for r in self._slot_req),
                "waiting": self._admit_q.qsize() + len(self._waiting),
                "max_seq_len": self.max_seq_len,
                "decode_chunk": self.decode_chunk,
                "inflight_chunks": sum(1 for e in self._inflight if e[0] == "chunk"),
                "submitted": self.submitted,
                "chunks": self._stat_chunks,
                "chunk_steps": self._stat_chunk_steps,
                "active_sum": self._stat_active_sum,  # raw: callers can delta
                "avg_active_at_dispatch": (
                    round(self._stat_active_sum / self._stat_chunks, 2)
                    if self._stat_chunks
                    else 0.0
                ),
                "prefill_waves": dict(sorted(self._stat_waves.items())),
                "wave_reqs": self._stat_wave_reqs,
                # token-budget step scheduler telemetry
                "scheduler": "chunked" if self.chunked else "wave",
                "steps": self._stat_steps,
                "step_tokens": self._stat_step_tokens,
                "step_token_budget": self.step_token_budget,
                "chunk_shapes": list(self.chunk_shapes),
                "prefilling": len(self._prefilling),
                # speculative decoding (gofr_tpu.spec)
                "spec": self._spec_summary(),
                # grammar-constrained decoding (gofr_tpu.structured)
                "constrained": self._constrained_summary(),
                # multi-tenant LoRA adapters (gofr_tpu.lora)
                "adapters": {
                    **(
                        self._lora_pool.snapshot() if self.lora_slots
                        else {"slots": 0, "resident": {}, "zombies": [],
                              "evictions": 0, "swaps": 0}
                    ),
                    "requests": self.adapter_requests,
                    "rank_max": self.lora_rank if self.lora_slots else 0,
                },
                "moe_experts": int(getattr(self.cfg, "n_experts", 0) or 0),
                "load_tokens": self.load_tokens(),
                "rejected": self.rejected,
                "shed": self.shed,
                "deadline_cancels": self.deadline_cancels,
                # overload-control telemetry (docs/advanced-guide/overload.md)
                "preemptions": self.preemptions,
                "sheds_predicted": self.sheds_predicted,
                "brownout_clamped": self.brownout_clamped,
                "predicted_wait_s": self.predicted_wait_s(),
                "overload": self.overload.snapshot(),
                "fairness": (
                    self.ledger.snapshot() if self.ledger is not None else None
                ),
                "draining": self._draining,
                "watchdog_trips": self.watchdog.trips if self.watchdog else 0,
                "numerical_trips": self.numerical_trips,
                "kvcache": self.kv.stats(),
                # recent-window phase latencies (seconds): exact p50/p99
                # over the last ~512 observations per phase
                "phases": {k: w.summary() for k, w in self._phases.items()},
                # utilization: analytic-FLOPs MFU + tokens/s/chip windows
                # and the roofline verdict (profiling.mfu)
                "mfu": self._mfu_summary(),
                # chip-time attribution + quota state (gofr_tpu.goodput)
                "goodput": (
                    self.goodput.snapshot()
                    if self.goodput is not None else None
                ),
                "quota": (
                    {**self.quota.snapshot(), "sheds": self.quota_sheds}
                    if self.quota is not None else None
                ),
                "warmup_s": self.warmup_s,
            }

    def usage_state(self) -> dict:
        """Windowed per-tenant usage + cumulative goodput attribution
        for the /.well-known/debug/usage endpoint (chargeback export).
        Same shape as ReplicatedLLMEngine.usage_state so the handler
        never branches on the engine kind."""
        usage = (
            self.usage.snapshot() if self.usage is not None
            else {"window_s": None, "tenants": {}}
        )
        return {
            "replicas": 1,
            "goodput": (
                self.goodput.snapshot() if self.goodput is not None else None
            ),
            "quota": (
                self.quota.snapshot() if self.quota is not None else None
            ),
            "quota_sheds": self.quota_sheds,
            **usage,
        }

    def set_tenant_quota(self, tenant: str, tok_s: float | None) -> None:
        """Set (or clear, with None) a tenant's hard token-rate quota at
        runtime — register_adapter's quota= knob lands here with the
        adapter's pseudo-client id."""
        if self.quota is not None:
            self.quota.set(tenant, tok_s)

    def debug_state(self) -> dict:
        """Live introspection for /.well-known/debug/engine: the slot
        table, in-flight device work, waiting requests, recent phase
        percentiles, and kv-cache residency. One lock acquisition; output
        is bounded (slots + at most 32 waiting entries) so the endpoint is
        safe to hit on a saturated engine."""
        now = time.perf_counter()

        def req_row(r: GenRequest, slot: int | None = None) -> dict:
            row = {
                "id": r.id,
                "phase": r.phase,
                "prompt_tokens": len(r.prompt_tokens),
                "prefill_pos": r.prefill_pos,
                "emitted": r.emitted,
                "max_new_tokens": r.max_new_tokens,
                "age_ms": (
                    round((now - r.submitted_at) * 1e3, 1)
                    if r.submitted_at is not None else None
                ),
                "prefix_hit": r.prefix_hit,
                "trace_id": r.span.trace_id if r.span is not None else "",
            }
            if slot is not None:
                row["slot"] = slot
            return row

        with self._lock:
            slot_table = [
                req_row(r, slot) if r is not None else None
                for slot, r in enumerate(self._slot_req)
            ]
            inflight = []
            entries = list(self._inflight)
            if self._processing is not None:
                entries.append(self._processing)
            for e in entries:
                if e[0] == "prefill":
                    inflight.append({
                        "kind": "prefill",
                        "requests": [r.id for _, r in e[2] if r is not None],
                        "wave": e[3]["nb"] or len(e[2]),
                        "bucket": e[3]["bucket"],
                        "age_ms": round((now - e[3]["t0"]) * 1e3, 1),
                    })
                elif e[0] == "step":
                    inflight.append({
                        "kind": "step",
                        "chunk_shape": e[6]["shape"],
                        "prefill_tokens": e[6]["prefill_tokens"],
                        "finishing": [r.id for _j, _s, r in e[2]],
                        "decode_steps": e[5],
                        "active": e[6]["active"],
                        "age_ms": round((now - e[6]["t0"]) * 1e3, 1),
                    })
                elif e[0] == "verify":
                    inflight.append({
                        "kind": "verify",
                        "requests": [r.id for _s, r in e[3]],
                        "draft": e[4]["W"] - 1,
                        "proposed": e[4]["proposed"],
                        "age_ms": round((now - e[4]["t0"]) * 1e3, 1),
                    })
                else:
                    inflight.append({
                        "kind": "chunk",
                        "steps": e[3],
                        "active": sum(r is not None for r in e[2]),
                        "age_ms": round((now - e[4]) * 1e3, 1),
                    })
            waiting_total = self._admit_q.qsize() + len(self._waiting)
            waiting = [req_row(r) for r in self._waiting[:32]]
            phases = {k: w.summary() for k, w in self._phases.items()}
        return {
            "label": self.label,
            "version": self.version,
            "tp_degree": self.tp_degree,
            "tp_overlap": self.tp_overlap,
            "role": self.role,
            "alive": self.alive(),
            "draining": self._draining,
            "died_reason": self.died_reason,
            "disconnect_cancels": self.disconnect_cancels,
            "watchdog": (
                {"threshold_s": self.step_watchdog_s,
                 "trips": self.watchdog.trips}
                if self.watchdog is not None else None
            ),
            "faults": self.faults.snapshot(),
            "deadline_cancels": self.deadline_cancels,
            "preemptions": self.preemptions,
            "sheds_predicted": self.sheds_predicted,
            "predicted_wait_s": self.predicted_wait_s(),
            "overload": self.overload.snapshot(),
            "fairness": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            "slots": self.slots,
            "active": sum(row is not None for row in slot_table),
            "max_seq_len": self.max_seq_len,
            "decode_chunk": self.decode_chunk,
            "scheduler": "chunked" if self.chunked else "wave",
            "step_token_budget": self.step_token_budget,
            "chunk_shapes": list(self.chunk_shapes),
            "prefilling": len(self._prefilling),
            "spec": self._spec_summary(),
            "constrained": self._constrained_summary(),
            "adapters": {
                **self.adapters(),
                "requests": self.adapter_requests,
                "rank_max": self.lora_rank if self.lora_slots else 0,
            },
            "moe_experts": int(getattr(self.cfg, "n_experts", 0) or 0),
            "slot_table": slot_table,
            "inflight": inflight,
            "waiting_total": waiting_total,
            "waiting": waiting,
            "admitting": self._admitting,
            "phases": phases,
            "slo": self.slo.snapshot() if self.slo is not None else None,
            "mfu": self._mfu_summary(),
            "goodput": (
                self.goodput.snapshot() if self.goodput is not None else None
            ),
            "usage": (
                self.usage.snapshot() if self.usage is not None else None
            ),
            "quota": (
                {**self.quota.snapshot(), "sheds": self.quota_sheds}
                if self.quota is not None else None
            ),
            "warmup_s": self.warmup_s,
            # this engine's rows from the process compile registry (the
            # full cross-engine view lives at /.well-known/debug/compiles)
            "compiles": self._registry.snapshot(model=self.label)["programs"],
            "submitted": self.submitted,
            "rejected": self.rejected,
            "shed": self.shed,
            "kvcache": self.kv.stats(),
        }

    # -- incident flight recorder (gofr_tpu.flightrec; docs/advanced-
    # guide/incident-debugging.md) ----------------------------------------

    def _inflight_requests(self) -> list[GenRequest]:
        """Racy, lock-free sweep of every live request — slotted, riding
        a device snapshot, prefilling, or waiting. Runs on the incident
        path where the engine lock may be wedged under a hung device
        call: a torn read (one request too many) beats a bundle dump
        that blocks behind the very hang it is documenting."""
        out: list[GenRequest] = []
        seen: set[int] = set()

        def take(r: GenRequest | None) -> None:
            if r is not None and r.id not in seen:
                seen.add(r.id)
                out.append(r)

        for r in list(self._slot_req):
            take(r)
        entries = list(self._inflight)
        proc = self._processing
        if proc is not None:
            entries.append(proc)
        for e in entries:
            try:
                for r in self._entry_requests(e):
                    take(r)
            except Exception:  # noqa: BLE001 — racy sweep, entries may be torn
                continue
        for r in list(self._prefilling):
            take(r)
        for r in list(self._waiting):
            take(r)
        return out

    def _hbm_samples(self) -> list[dict]:
        """Per-device HBM occupancy for the bundle (the telemetry
        poller's sample shape, taken inline — the poller may be off)."""
        import jax

        out = []
        for d in jax.devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — backends without memory_stats
                stats = {}
            out.append({
                "device": d.id,
                "platform": getattr(d, "platform", ""),
                "kind": getattr(d, "device_kind", ""),
                "bytes_in_use": stats.get("bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            })
        return out

    def _config_fingerprint(self) -> dict:
        """The engine's serving shape plus a content hash: 'is the
        replay host configured like the incident host' is the first
        question a post-mortem asks, and diffing two fingerprints
        answers it without eyeballing forty knobs."""
        import hashlib as _hashlib
        import json as _json

        shape = {
            "model": self.label,
            "version": self.version,
            "role": self.role,
            "slots": self.slots,
            "max_seq_len": self.max_seq_len,
            "decode_chunk": self.decode_chunk,
            "chunked": self.chunked,
            "speculative": self.speculative,
            "spec_draft": self.spec_draft,
            "constrained": self.constrained,
            "lora_slots": self.lora_slots,
            "quantized": self.quantized,
            "kv_paged": self.kv.paged,
            "kv_window": self.kv.window,
            "tp_degree": self.tp_degree,
            "flight_records": self.flightrec.capacity,
            "flight_redact": self.flightrec.redact,
            "wide_event_sample": self._wide_sample,
        }
        shape["sha256"] = _hashlib.sha256(
            _json.dumps(shape, sort_keys=True, default=repr).encode()
        ).hexdigest()
        return shape

    def _incident(
        self, trigger: str, *, reason: str = "", lock_timeout: float = 2.0
    ) -> str | None:
        """Dump one black-box bundle (gofr_tpu.flightrec.BlackboxDumper):
        engine debug state, the trace ring, the retained wide events,
        the compile registry, HBM occupancy, the config fingerprint, and
        the flight records of everything in flight. Returns the bundle
        path, or None when the dumper is unarmed or the trigger class is
        inside its rate-limit window. Never raises — the incident path
        must not add a second failure to the first."""
        if not self.blackbox.enabled():
            return None
        try:
            sections: dict[str, Any] = {}
            # engine state under a BOUNDED acquire: the incident may BE a
            # wedged device call that still holds the lock (RLock, so an
            # under-lock caller like the SLO flip re-enters instantly)
            if self._lock.acquire(timeout=lock_timeout):
                try:
                    sections["debug_state"] = self.debug_state()
                finally:
                    self._lock.release()
            else:
                sections["debug_state"] = {
                    "lock_wedged": True,
                    "died": self._died,
                    "died_reason": self.died_reason,
                }
            ring = getattr(self.tracer, "ring", None) if self.tracer else None
            if ring is not None:
                sections["traces"] = {
                    "stats": ring.stats(),
                    "trace_ids": ring.trace_ids(64),
                    "spans": ring.dump(512),
                }
            sections["wide_events"] = list(self._wide_retained)
            sections["compiles"] = self._registry.snapshot(model=self.label)
            sections["hbm"] = self._hbm_samples()
            sections["config"] = self._config_fingerprint()
            if self.anomaly is not None:
                sections["anomaly"] = self.anomaly.snapshot()
            records = self.flightrec.snapshot_inflight(self._inflight_requests())
            records.extend(self.flightrec.records(limit=64, final=True))
            return self.blackbox.dump(
                trigger, reason=reason, sections=sections, records=records
            )
        except Exception as e:  # noqa: BLE001 — see docstring
            if self.logger is not None:
                self.logger.error(f"black-box bundle capture failed: {e!r}")
            return None

    def replay(self, record_or_id, *, timeout: float = 120.0) -> dict:
        """Deterministically re-execute a recorded request with pinned
        version/adapter/grammar/seed and report the first-divergence
        token index vs the recorded emission (gofr_tpu.flightrec;
        `replay` CLI subcommand / POST /.well-known/debug/replay)."""
        from .flightrec import replay_record

        rec = record_or_id
        if not isinstance(rec, dict):
            rec = self.flightrec.get(int(record_or_id))
            if rec is None:
                return {
                    "id": record_or_id,
                    "error": "no flight record with that id (ring holds "
                             f"{len(self.flightrec)} of "
                             f"{self.flightrec.capacity})",
                }
        return replay_record(self, rec, timeout=timeout)

    def _spec_summary(self) -> dict:
        """Speculative-decoding telemetry block for stats()/debug_state:
        cheap counter reads, no lock requirements (GIL-atomic ints).
        The constrained split is what the structured-decoding bench
        point reads — acceptance on grammar-masked text should meet or
        beat the unconstrained rate (the drafter's proposals are
        pre-filtered by the same DFA)."""
        prop_u = self.spec_proposed - self.spec_proposed_c
        acc_u = self.spec_accepted - self.spec_accepted_c
        return {
            "enabled": self.speculative,
            "draft": self.spec_draft,
            "steps": self.spec_steps,
            "proposed": self.spec_proposed,
            "accepted": self.spec_accepted,
            "plain_lanes": self.spec_plain,
            "accept_rate": (
                round(self.spec_accepted / self.spec_proposed, 3)
                if self.spec_proposed else None
            ),
            "constrained": {
                "proposed": self.spec_proposed_c,
                "accepted": self.spec_accepted_c,
                "accept_rate": (
                    round(self.spec_accepted_c / self.spec_proposed_c, 3)
                    if self.spec_proposed_c else None
                ),
            },
            "unconstrained": {
                "proposed": prop_u,
                "accepted": acc_u,
                "accept_rate": (
                    round(acc_u / prop_u, 3) if prop_u else None
                ),
            },
        }

    # -- grammar-constrained decoding (gofr_tpu.structured) ---------------

    def _constrained_summary(self) -> dict:
        """Telemetry block for stats()/debug_state (lock held by caller
        or freshness unimportant — counter reads are GIL-atomic)."""
        return {
            "enabled": self.constrained,
            "requests": self.constrained_requests,
            "grammars_resident": sum(
                1 for g in self._grammars if g is not None
            ),
            "grammar_cap": self._g_cap,
            "states": [
                g.n_states if g is not None else 0 for g in self._grammars
            ],
        }

    def _register_grammar(self, g) -> int:
        """Resident-grammar table slot for one TokenGrammar (call with
        the engine lock held). Repeat schemas dedup by grammar key; a
        full table evicts a zero-ref entry, and a table whose every slot
        holds live requests sheds the submit (429 — capacity, not a
        client bug)."""
        vocab = getattr(g, "vocab_size", None)
        if vocab != self.cfg.vocab_size:
            raise ValueError(
                f"grammar compiled for vocab {vocab}, model vocab is "
                f"{self.cfg.vocab_size} — compile against this model's "
                "tokenizer"
            )
        for i, og in enumerate(self._grammars):
            if og is not None and og.key == g.key:
                return i
        gid = None
        if len(self._grammars) < self._g_cap:
            self._grammars.append(None)
            self._g_refs.append(0)
            gid = len(self._grammars) - 1
        else:
            for i, og in enumerate(self._grammars):
                if self._g_refs[i] == 0:
                    gid = i
                    break
        if gid is None:
            raise EngineOverloaded(
                f"all {self._g_cap} resident grammar slots hold live "
                "requests (raise TPU_LLM_CONSTRAINED_GRAMMARS)",
                retry_after=1.0,
            )
        self._grammars[gid] = g
        self._g_refs[gid] = 0
        self._rebuild_grammar_table()
        return gid

    def _rebuild_grammar_table(self) -> None:
        """Re-pad + re-ship the resident grammar table. Padded to
        power-of-two grammar count and state count so the constrained
        program family retraces O(log) times over an engine's life, not
        per registration; padding rows/states admit nothing (-1), which
        reads as 'dead' and is never reachable for a live lane."""
        jnp = self._jnp
        live = [g for g in self._grammars if g is not None]
        if not live:
            self._gr_dev = None
            if self.metrics is not None:
                self.metrics.set_gauge(
                    "app_llm_constrained_grammars", 0.0, model=self.label
                )
            return
        G = len(self._grammars)
        gp = 1 << max(0, G - 1).bit_length()
        smax = max(g.n_states for g in live)
        sp = max(32, 1 << max(0, smax - 1).bit_length())
        tab = np.full((gp, sp, self.cfg.vocab_size), -1, np.int32)
        for i, g in enumerate(self._grammars):
            if g is not None:
                tab[i, : g.n_states, :] = g.table
        arr = jnp.asarray(tab)
        if self.device is not None:
            arr = self._jax.device_put(arr, self.device)
        self._gr_dev = arr
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_constrained_grammars", float(len(live)),
                model=self.label,
            )

    def _grammar_live(self) -> bool:
        """Any resident request constrained? (lock held). True routes
        EVERY device dispatch through the constrained program family —
        the per-slot gid mask keeps unconstrained lanes token-identical,
        and one family per dispatch keeps the DFA state chain coherent."""
        return any(
            r is not None and r.grammar is not None for r in self._slot_req
        ) or any(r.grammar is not None for r in self._prefilling)

    def _gids_np(self) -> np.ndarray:
        """Per-slot grammar selector for one dispatch (lock held):
        -1 = unconstrained lane (logits untouched)."""
        gids = np.full((self.slots,), -1, np.int32)
        for i, r in enumerate(self._slot_req):
            if r is not None and r.grammar is not None and r._g_id >= 0:
                gids[i] = r._g_id
        return gids

    # -- multi-tenant LoRA adapter lifecycle (gofr_tpu.lora;
    # docs/advanced-guide/multi-tenancy.md) ------------------------------
    def _require_lora(self) -> None:
        if not self.lora_slots:
            raise ValueError(
                "engine has no adapter pool (lora_slots=0; set "
                "TPU_LLM_LORA_SLOTS or pass lora_slots=)"
            )

    def _lora_stage(self, gid: int, canon: dict) -> None:
        """Write one adapter's padded (A, B) pairs into table row ``gid``
        (every target; absent targets write zeros so residue from the
        row's previous tenant can never leak into this one). The gid is
        TRACED, so every load on an engine's life reuses the same
        compiled set programs; params is never donated, so the rebuild
        is a dict swap around fresh table buffers and the serving jit
        caches stay warm."""
        jnp = self._jnp
        op = self._lora_set_ops.get("set")
        if op is None:
            def _set(tab, g, sl):
                return tab.at[:, g].set(sl)

            op = self._jax.jit(_set)
            self._lora_set_ops["set"] = op
        L, rmax = self.cfg.n_layers, self.lora_rank
        layers = dict(self.params["layers"])
        gid_dev = jnp.asarray(gid, jnp.int32)
        for name, (d_in, d_out) in self._lora_mod.target_dims(
            self.cfg
        ).items():
            ka, kb = f"lora_{name}_a", f"lora_{name}_b"
            if ka not in layers:
                continue
            a_pad = np.zeros((L, d_in, rmax), np.float32)
            b_pad = np.zeros((L, rmax, d_out), np.float32)
            if name in canon:
                a, b = canon[name]
                r = a.shape[2]
                a_pad[:, :, :r] = a
                b_pad[:, :r, :] = b
            layers[ka] = op(layers[ka], gid_dev, jnp.asarray(a_pad))
            layers[kb] = op(layers[kb], gid_dev, jnp.asarray(b_pad))
        # atomic publish of the new tables: dispatches read self.params
        # once per call, and the staged gid has no live lane (refs == 0
        # by allocate's contract), so a dispatch racing this swap serves
        # every resident tenant identically from either dict
        self.params = {**self.params, "layers": layers}

    def load_adapter(
        self, name: str, adapter: dict, *, version: str = "v1",
        alpha: float | None = None, fair_weight: float | None = None,
    ) -> int:
        """Validate ``adapter`` against the base config, bind ``name`` to
        a pool gid (LRU-evicting an idle resident when full), and stage
        its delta into the device tables. Callable while serving: the
        staged gid has no in-flight lane until a submit names it. Returns
        the gid. ``fair_weight`` sets the per-tenant FairLedger share of
        the adapter's pseudo-client (``adapter:<name>``)."""
        self._require_lora()
        canon = self._lora_mod.validate_adapter(
            self.cfg, adapter, rank_max=self.lora_rank, alpha=alpha
        )
        rank = max((a.shape[2] for a, _ in canon.values()), default=0)
        with self._lock:
            ev0 = self._lora_pool.evictions
            gid = self._lora_pool.allocate(name, version=version, rank=rank)
            evicted = self._lora_pool.evictions - ev0
        try:
            self._lora_stage(gid, canon)
        except BaseException:
            with self._lock:
                self._lora_pool.remove(name)
            raise
        if fair_weight is not None and self.ledger is not None:
            self.ledger.set_weight(f"adapter:{name}", fair_weight)
        if self.metrics is not None:
            if evicted:
                self.metrics.increment_counter(
                    "app_llm_adapter_evictions_total", float(evicted),
                    model=self.label,
                )
            self.metrics.set_gauge(
                "app_llm_adapters_resident", float(len(self._lora_pool)),
                model=self.label,
            )
        return gid

    def publish_adapter(self, staging: str, name: str) -> int | None:
        """Atomically repoint ``name`` at the gid staged under
        ``staging`` (hot-load commit after a canary gate). In-flight
        requests keep decoding on the OLD gid until they drain (zombie);
        new submits resolve to the new one. Returns the previous gid or
        None for a first load."""
        self._require_lora()
        with self._lock:
            old = self._lora_pool.publish(staging, name)
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_adapter_swaps_total", model=self.label,
            )
            self.metrics.set_gauge(
                "app_llm_adapters_resident", float(len(self._lora_pool)),
                model=self.label,
            )
        return old

    def evict_adapter(self, name: str) -> int:
        """Unbind ``name`` (retire / canary reject). Its gid frees
        immediately when idle, else drains as a zombie while in-flight
        requests finish — the table row is not zeroed (no lane points at
        it; the next allocate overwrites it wholesale)."""
        self._require_lora()
        with self._lock:
            gid = self._lora_pool.remove(name)
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_adapters_resident", float(len(self._lora_pool)),
                model=self.label,
            )
        return gid

    def adapters(self) -> dict:
        """Pool snapshot: resident adapters (gid/version/rank/refs),
        zombie gids, lifetime eviction/swap counts. Empty-shaped on
        engines without a pool so registry listings need no feature
        probe."""
        if not self.lora_slots:
            return {
                "slots": 0, "resident": {}, "zombies": [],
                "evictions": 0, "swaps": 0,
            }
        with self._lock:
            return self._lora_pool.snapshot()

    def _ensure_c_ops(self) -> None:
        """Build (and on first dispatch, compile) the constrained program
        family. Lazy by design: engines that never see a grammar build
        nothing, and the first constrained request pays the compile the
        way the monolithic prefill family already does in chunked mode."""
        if self._chunk_ops_c:
            return
        self._chunk_ops_c = {
            k: self._mk_chunk_c(k) for k in self._chunk_ops
        }
        if self.chunked:
            self._step_ops_c = {
                s: self._mk_step_c(s) for s in self._step_ops
            }
        if self._verify_op is not None:
            self._verify_op_c = self._mk_verify_c()

    def load(self) -> int:
        """Cheap routing signal for the replica router: occupants plus
        queue depth plus requests mid-admission (sliced out of _waiting,
        not yet slotted). Lock-free — _slot_req is only ever mutated in
        place (no resize), so a torn read costs at most a stale unit."""
        return (
            sum(r is not None for r in self._slot_req)
            + self._admit_q.qsize()
            + len(self._waiting)
            + self._admitting
        )

    def resident_slots(self) -> int:
        """Occupied decode slots RIGHT NOW — the decode-role routing
        signal (disaggregated serving admits decode work by slot
        residency, where the prefill role routes by queued prompt
        tokens). Lock-free like load(): _slot_req is mutated in place,
        a torn read costs at most one stale unit."""
        return sum(r is not None for r in self._slot_req)

    def load_tokens(self) -> int:
        """Token-weighted routing signal: the estimated device work still
        owed to every live request — prompt remainder plus expected decode
        — maintained as a counter (submit adds prompt + max_new; prefill
        chunks and emitted tokens credit it back; terminal paths flush the
        residue). A 128-token prompt weighs 16x an 8-token prompt here
        where load() weighs them identically, which is what the replica
        router actually needs to balance. Lock-free read of a single int
        (torn reads cost at most one stale request)."""
        return max(0, self._load_tokens)

    def predicted_wait_s(self) -> float | None:
        """Predicted queue wait for a NEW request: the outstanding token
        estimate (load_tokens) over the measured serving throughput (EMA
        over recent device windows). None until the first window lands —
        the overload controller treats that as no pressure, so a cold
        engine never sheds. An estimate, not a promise: pipelined
        windows overlap, so the EMA reads slightly low and the
        prediction slightly high (conservative for shedding)."""
        tput = self._tput_ema
        if not tput or tput <= 1e-9:
            return None
        return self.load_tokens() / tput

    def throughput_tok_s(self) -> float | None:
        """Measured serving throughput (EMA over recent device windows;
        None until the first window). The front router pools this across
        engine PROCESSES to price fleet admission the same way one
        engine prices its own (docs/advanced-guide/scale-out.md)."""
        return self._tput_ema

    def _observe_tput(self, tokens: int, dt: float) -> None:
        """Fold one finished device window (tokens served / wall) into
        the throughput EMA that prices predicted queue wait. Lock-free
        float write (a torn read costs one stale estimate)."""
        if tokens <= 0 or dt <= 0:
            return
        rate = tokens / dt
        ema = self._tput_ema
        self._tput_ema = rate if ema is None else 0.8 * ema + 0.2 * rate

    def _load_credit(self, r: GenRequest, n: int) -> None:
        """Retire `n` tokens of r's outstanding-work estimate (bounded by
        what it still owes). Call with the lock held."""
        n = min(n, r._load_acct)
        if n > 0:
            r._load_acct -= n
            self._load_tokens -= n

    def alive(self) -> bool:
        """Health signal for the replica router: the engine accepts work
        only while both its threads run and neither close() nor a terminal
        thread failure (_die) has begun."""
        return (
            not self._stop
            and self._thread.is_alive()
            and self._collector.is_alive()
        )

    def accepting(self) -> bool:
        """Routing signal: alive AND taking new work (a draining replica
        finishes its in-flight requests but must not be fed more)."""
        return self.alive() and not self._draining

    def drain(self) -> None:
        """Graceful-drain entry (rolling deploy): close admission —
        submit() raises EngineDraining (503) — while every slotted and
        queued request runs to completion. The app lifecycle polls
        drained() under GOFR_DRAIN_DEADLINE_S and then close()s."""
        self._draining = True
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_drain_state", 1.0, model=self.label
            )
        self._kick.set()

    def undrain(self) -> None:
        """Reopen admission after a drain that was ROLLED BACK rather
        than completed — the rollout controller's single-engine rollback
        path (docs/advanced-guide/rollouts.md). A no-op on a dead engine
        (alive() is still False; the router will not route here)."""
        self._draining = False
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_drain_state", 0.0, model=self.label
            )
        self._kick.set()

    def drained(self) -> bool:
        """True once no request holds a slot, waits, or is in flight.
        A DEAD engine is vacuously drained — its requests were rescued
        or closed by _die, and in the wedged-lock watchdog case the lock
        below is held forever by the hung device call (the drain poll
        must not block on a corpse)."""
        if self._died:
            return True
        with self._lock:
            return (
                self.load() == 0
                and not self._inflight
                and self._processing is None
            )

    # -- fault-injection seams (gofr_tpu.resilience.faults) ---------------
    def _fault(self, point: str) -> None:
        """Raise-kind seam: InjectedFault when `point` is armed for this
        engine label. Disarmed cost: one dict lookup."""
        spec = self.faults.take(point, self.label)
        if spec is None:
            return
        self._count_fault(point)
        from .resilience import InjectedFault

        raise InjectedFault(spec.message)

    def _fault_latency(self) -> None:
        """Sleep-kind seam: a wedged device transfer, as the host sees
        one — the blocking happens outside the engine lock, exactly where
        a real fetch blocks, so the step watchdog can convert it."""
        spec = self.faults.take("step_latency", self.label)
        if spec is None:
            return
        self._count_fault("step_latency")
        from .resilience.faults import sleep_for

        sleep_for(spec)

    def _count_fault(self, point: str) -> None:
        if self.logger is not None:
            self.logger.warn(f"fault injection: {point} fired on {self.label}")
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_faults_injected_total", point=point, model=self.label
            )

    def _poison_fault(self) -> bool:
        """Poison-payload seam (scheduler pass): a ``device_step`` spec
        armed WITH A TAG fires exactly when a resident request carries
        the same tag — the deterministic stand-in for a payload whose
        content reliably crashes the step program. Terminal like
        replica_kill (the poison scenario is a replica-killing payload,
        not a transient step error); the router's poison quarantine then
        bounds the payload's blast radius. Disarmed cost: one dict
        lookup."""
        if not self.faults.has_tagged("device_step"):
            return False
        with self._lock:
            resident = [r for r in self._slot_req if r is not None]
            resident.extend(self._prefilling)
        for r in resident:
            tag = getattr(r, "tag", "")
            if tag and self.faults.take("device_step", self.label, tag=tag):
                self._count_fault("device_step")
                self._die(
                    f"poison payload: device_step fired for tagged request "
                    f"(tag={tag!r})"
                )
                return True
        return False

    def _numeric_trip(self, where: str) -> None:
        """Non-finite logits reached a fetched token array: convert the
        garbage stream into a replica death with a distinct,
        classifiable reason — the failover path re-seeds the in-flight
        requests on a replica whose compute is not poisoned, and the
        device ledger bills the trip as "numerical"."""
        self.numerical_trips += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_numerical_trips_total", model=self.label
            )
        self._die(f"numerical watchdog: non-finite logits ({where})")

    def _numeric_check_fetch(self, arr, cols: list[int], where: str):
        """Collector-side sentinel scan over one fetched token array
        (``cols`` are the last-axis lanes owned by live requests —
        inactive lanes legitimately carry garbage). Also hosts the
        ``nan_logits`` chaos seam: an armed spec corrupts one live lane
        with the sentinel, exactly what NaN logits produce on device —
        with the watchdog disabled the corruption streams through to the
        caller, which is the silent failure the watchdog exists to stop.
        Returns ``(arr, tripped)``; on a trip the engine is already
        dying and the caller must not emit."""
        if not cols:
            return arr, False
        if self.faults.take("nan_logits", self.label) is not None:
            self._count_fault("nan_logits")
            arr = np.array(arr)  # device fetches can be read-only views
            arr[..., cols[0]] = -1
        if self.numeric_check and bool((arr[..., cols] == -1).any()):
            self._numeric_trip(where)
            return arr, True
        return arr, False

    def _zero_state_gauges(self) -> None:
        """A stopped engine must not keep exporting its last live
        occupancy/backlog — dashboards and autoscaling would read load
        from an engine that no longer exists (same rationale as
        CacheManager.close() zeroing its resident-bytes gauge)."""
        if self.metrics is None:
            return
        for name in (
            "app_llm_slots_in_use",
            "app_llm_queue_depth",
            "app_llm_admission_backlog",
            "app_llm_step_budget_utilization",
            "app_llm_drain_state",
            "app_llm_brownout_state",
            "app_llm_fairness_debt",
            "app_llm_spec_accept_rate",
            "app_llm_constrained_grammars",
            "app_llm_adapters_resident",
            "app_llm_moe_experts",
        ):
            self.metrics.set_gauge(name, 0.0, model=self.label)
        # goodput ratio is load state too: a dead engine must not freeze
        # its last useful-fraction on the exposition (close() AND _die()
        # both funnel here — the PR 3/PR 18 regression class)
        if self.goodput is not None:
            self.goodput.zero_gauges()
        # a closed engine must not keep exporting its version row (the
        # dead-engine gauge bug class): the series would read as "this
        # label still serves version X" forever
        self.metrics.set_gauge(
            "app_llm_model_version_info", 0.0,
            model=self.label, version=self.version,
        )
        # SLO burn state is load state: a dead engine must not hold
        # "fast burn" (health would stay degraded forever) nor keep its
        # last burn rate on the dashboard; windows clear so a restarted
        # engine starts on a clean error budget
        if self.slo is not None:
            self.slo.zero_gauges()
        # same class: a dead engine must not hold an anomaly flag — the
        # degraded-backend signal would outlive the backend
        if self.anomaly is not None:
            self.anomaly.zero_gauges()

    def _teardown_profiling(self) -> None:
        """Compile-observatory teardown (close() and _die()): drop this
        engine's registry rows and zero its utilization gauges — a dead
        engine must neither list its programs at /debug/compiles nor keep
        exporting its last MFU (the slot-gauge bug class all over again)."""
        self._registry.remove_model(self.label)
        if self.metrics is None:
            return
        for phase in ("prefill", "decode"):
            self.metrics.set_gauge(
                "app_llm_mfu", 0.0, model=self.label, phase=phase
            )
            self.metrics.set_gauge(
                "app_llm_roofline_ratio", 0.0, model=self.label, phase=phase
            )
        self.metrics.set_gauge(
            "app_llm_tokens_per_second_per_chip", 0.0, model=self.label
        )

    def close(self) -> None:
        self._stop = True
        self._fail_sched_work()  # handoff waiters fail fast, not by timeout
        self._admit_q.put(None)
        self._kick.set()
        with self._work_cv:
            self._work_cv.notify_all()
        self._thread.join(timeout=10)
        with self._work_cv:
            self._work_cv.notify_all()
        self._collector.join(timeout=15)
        self._abort_all()
        self._drain_pending()
        self._zero_state_gauges()
        self._teardown_profiling()
        if self.ledger is not None:
            # a closed replica must not pin the fleet ledger's
            # new-arrival floor with a stale waiting-client set
            self.ledger.set_active(self.label, set())
        # flight-recorder teardown: no further bundles (the close()/_die()
        # contract), and the record ring clears WITH the engine — unlike
        # _die, where the ring outlives the death for post-mortems (the
        # bundle was already dumped by then)
        self.blackbox.close()
        self.flightrec.clear()
        self.kv.close()  # drop retained prefix rows (device buffers)

    def _drain_pending(self) -> None:
        """End-of-stream every request still in the waiting list or the
        admit queue (shared by close() and _die()): consumers see a
        'cancelled' finish instead of blocking until stream timeout."""
        with self._lock:
            waiting, self._waiting = self._waiting, []
        now = time.perf_counter()
        for r in waiting:
            if r.finish_reason is None:
                r.finish_reason = "cancelled"
                self._observe_finish(r, now)
                r.out.put(None)
        while True:
            try:
                r = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if r is not None and r.finish_reason is None:
                r.finish_reason = "cancelled"
                self._observe_finish(r, now)
                r.out.put(None)
        if self.logger is not None:
            self._flush_wide_events()

    # -- engine internals -------------------------------------------------
    def _warm(self) -> None:
        """Compile every serving executable before traffic arrives. The
        compiles run CONCURRENTLY on a small thread pool: XLA releases the
        GIL while compiling and each jitted function owns its own cache
        entry, so the prefill variants, the decode chunk, and the admission
        ops overlap instead of serializing (r2's sequential warm took ~21 s;
        overlapped it is bounded by the slowest single program)."""
        from concurrent.futures import ThreadPoolExecutor

        jnp = self._jnp
        t0 = time.perf_counter()
        zero_rng = self._rng
        meta = jnp.zeros((3, self.admit_cap), jnp.int32)

        def warm_prefill(nb: int, b: int):
            pack = jnp.zeros((nb, b + 2), jnp.int32).at[:, -2].set(1)
            first, c, _logits, _ = self._prefill_op(self.params, pack, zero_rng)
            return first, c

        def warm_hit_first(nb: int):
            self._hit_first_op(
                jnp.zeros((nb, self.cfg.vocab_size), jnp.float32),
                jnp.zeros((nb,), jnp.float32), zero_rng,
            )

        # every power-of-two admission width (wave sizing in _admit)
        nbs: list[int] = []
        nb = 1
        while nb < self.admit_cap:
            nbs.append(nb)
            nb <<= 1
        nbs.append(self.admit_cap)

        def warm_cache_ops():
            """insert + admit_update at every admission width, the
            unified-step programs at every (chunk shape, width,
            piggyback) combination, then the decode chunk — CHAINED
            through the real slot cache by donation, exactly like live
            serving, so warm's peak memory never holds a second full-size
            cache and no two ops donate the same buffer. (The chain also
            serializes the step-program compiles; the wave path's
            prefill-family overlap does not apply here and the cost lands
            in warmup_s.)"""
            cache = self.cache
            tail = jnp.zeros((self.slots,), jnp.int32)
            active = jnp.zeros((self.slots,), bool)
            temps = jnp.zeros((self.slots,), jnp.float32)
            if self.kv.paged:
                # paged program family: same chain, pool-layout operands.
                # Zero tables/live/packs make every write a dropped
                # scatter — block 0 is never touched, state stays zeros.
                scales = self._kv_scales
                tables = jnp.zeros(
                    (self.slots, self.kv.table_width), jnp.int32
                )
                live = jnp.zeros((self.slots,), bool)
                M = self.admit_cap
                oob_b = self.kv.pool.n_blocks
                for nb in nbs:
                    scratch = self.kv.init_cache(nb)
                    cache, scales = self._insert_paged_op(
                        cache, scales, scratch, meta[:2], tables
                    )
                    cache, scales = self._seed_op(
                        cache, scales,
                        jnp.full((M,), oob_b, jnp.int32),
                        jnp.full((M,), oob_b, jnp.int32),
                        jnp.full((M,), self.slots, jnp.int32),
                        jnp.zeros((M,), jnp.int32),
                    )
                    self._admit_update(
                        jnp.zeros((self.slots,), jnp.int32),
                        jnp.zeros((self.slots,), bool),
                        jnp.zeros((self.slots,), jnp.float32),
                        jnp.zeros((nb,), jnp.int32), meta,
                    )
                for shape, op in sorted(self._step_ops.items()):
                    for nb in nbs:
                        pack = jnp.zeros((nb, shape + 3), jnp.int32)
                        smeta = jnp.full((2, nb), self.slots, jnp.int32).at[1].set(0)
                        _f, _kept, _toks, tail, cache, scales, active, temps, _ = op(
                            self.params, cache, scales, tables, live,
                            tail, active, temps, pack, smeta, zero_rng,
                        )
                if self._verify_op is not None:
                    vpack = jnp.zeros(
                        (self.slots, self.spec_draft + 2), jnp.int32
                    )
                    _ys, _acc, cache, scales, tail, _ = self._verify_op(
                        self.params, cache, scales, tables, tail, temps,
                        vpack, zero_rng,
                    )
                for op in self._chunk_ops.values():
                    toks, last, cache, scales, _ = op(
                        self.params, tail, cache, scales, tables, live,
                        active, temps, zero_rng,
                    )
                self._kv_scales = scales
                return last, cache
            for nb in nbs:
                scratch = self.kv.init_cache(nb)
                cache = self._insert_many(cache, scratch, meta)
                self._admit_update(
                    jnp.zeros((self.slots,), jnp.int32),
                    jnp.zeros((self.slots,), bool),
                    jnp.zeros((self.slots,), jnp.float32),
                    jnp.zeros((nb,), jnp.int32), meta,
                )
            for shape, op in sorted(self._step_ops.items()):
                for nb in nbs:
                    pack = jnp.zeros((nb, shape + 3), jnp.int32)
                    smeta = jnp.full((2, nb), self.slots, jnp.int32).at[1].set(0)
                    _f, _kept, _toks, tail, cache, active, temps, _ = op(
                        self.params, cache, tail, active, temps,
                        pack, smeta, zero_rng,
                    )
            if self._verify_op is not None:
                # speculative verify program: one full-batch executable,
                # chained through the donated cache/tail like the rest.
                # All-unselected pack: no lane writes, state unchanged.
                vpack = jnp.zeros((self.slots, self.spec_draft + 2), jnp.int32)
                _ys, _acc, cache, tail, _ = self._verify_op(
                    self.params, cache, tail, temps, vpack, zero_rng,
                )
            for op in self._chunk_ops.values():
                toks, last, cache, _ = op(
                    self.params, tail, cache, active, temps, zero_rng,
                )
            return last, cache

        n_step_tasks = len(self._step_ops) * len(nbs)
        if self.chunked:
            # chunked mode: the monolithic prefill family exists (bench
            # probes and the A/B lever call it) but is compiled lazily —
            # warming it would double the cold-start bill for programs
            # live traffic never dispatches
            n_tasks = 1 + n_step_tasks
        else:
            n_tasks = len(self.prefill_buckets) * len(nbs) + 1
        if self._verify_op is not None:
            n_tasks += 1  # the speculative verify program (either scheduler)
        if self._hit_first_op is not None:
            n_tasks += len(nbs)
        # Sharded programs on the CPU backend (8-virtual-device test mesh)
        # must warm SEQUENTIALLY: concurrent sharded executions deadlock on
        # the per-device thread pool there (each execution parks waiting
        # for device workers another execution holds). Live serving is
        # unaffected — the scheduler is the only thread that executes
        # programs. Real-TPU warms keep the full overlap.
        workers = (
            1 if self._sharded and self._jax.default_backend() == "cpu"
            else n_tasks
        )
        with ThreadPoolExecutor(max_workers=workers) as pool:
            futs = [pool.submit(warm_cache_ops)]
            if not self.chunked:
                for b in self.prefill_buckets:
                    for nb in nbs:
                        futs.append(pool.submit(warm_prefill, nb, b))
            if self._hit_first_op is not None:
                for nb in nbs:
                    futs.append(pool.submit(warm_hit_first, nb))
            last, cache = futs[0].result()
            for f in futs[1:]:
                f.result()
        _ = np.asarray(last)  # sync (block_until_ready is unreliable on axon)
        # the chain donated self.cache; adopt the output (zeros in, zeros
        # out — only length needs resetting)
        self.cache = cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        # Warmup cost into the compile registry: this is the bill a cold
        # restart pays before the first request, invisible in benches until
        # BENCH_r07 (wall time — the pool overlaps compiles, so it is NOT
        # the per-program sum the registry rows add up to).
        self.warmup_s = time.perf_counter() - t0
        self._registry.record_warmup(self.label, self.warmup_s, programs=n_tasks)
        if self.logger is not None:
            sched = (
                f"chunk shapes {self.chunk_shapes}, "
                f"step budget {self.step_token_budget}"
                if self.chunked else f"buckets {self.prefill_buckets}"
            )
            self.logger.info(
                f"LLM engine warmed in {self.warmup_s:.1f}s "
                f"({sched}, slots {self.slots}, "
                f"decode chunk {self.decode_chunk})"
            )

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.max_seq_len

    def _wave_width(self, n: int) -> int:
        """Admission-wave batch dim: next power of two, capped at
        admit_cap — a wave of 2 must not pay the admit_cap-padded prefill
        (measured nb=1: 4.3 ms, nb=16: 30.5 ms; mid-load throughput
        collapsed when every trickle wave compiled/ran at full width).
        Bounded executable count: log2(admit_cap)+1 variants per bucket
        (and per hit-sample op), all pre-warmed — _warm enumerates the
        SAME widths, so any change here must change there too."""
        return min(self.admit_cap, 1 << max(0, n - 1).bit_length())

    def _inflight_steps(self) -> dict[int, int]:
        """Per-slot decode steps already dispatched for the CURRENT owner.
        Includes the entry the collector popped but has not emitted yet
        (its tokens are still coming). Call with the lock held."""
        steps: dict[int, int] = {}
        entries = list(self._inflight)
        if self._processing is not None:
            entries.append(self._processing)
        for e in entries:
            if e[0] == "prefill":
                # an un-fetched prefill entry carries each request's first
                # token — without counting it, demand is overestimated by 1
                # per fresh request and an extra decode chunk occasionally
                # dispatched
                for slot, r in e[2]:
                    if r is not None and r is self._slot_req[slot]:
                        steps[slot] = steps.get(slot, 0) + 1
                continue
            if e[0] == "verify":
                # a verify's yield is data-dependent (1..draft+1 tokens);
                # count the GUARANTEED minimum of one — overcounting
                # could virtually free a slot on tokens that never
                # arrive, stranding the request without an end-of-stream.
                # The 1-token floor also keeps the slot ineligible for
                # another verify until this one is fetched.
                for slot, r in e[3]:
                    if r is self._slot_req[slot]:
                        steps[slot] = steps.get(slot, 0) + 1
                continue
            if e[0] == "step":
                # unified step: each finishing row carries its first token,
                # and the piggybacked decode part carries k per snapshot slot
                _, _first, finishes, _toks, snapshot, k, _info = e
                for _j, slot, r in finishes:
                    if r is self._slot_req[slot]:
                        steps[slot] = steps.get(slot, 0) + 1
                if k and snapshot is not None:
                    for slot, r in enumerate(snapshot):
                        if r is not None and r is self._slot_req[slot]:
                            steps[slot] = steps.get(slot, 0) + k
                continue
            snapshot, k = e[2], e[3]
            for slot, r in enumerate(snapshot):
                if r is not None and r is self._slot_req[slot]:
                    steps[slot] = steps.get(slot, 0) + k
        return steps

    def _free_slots(self) -> list[int]:
        """Free or VIRTUALLY free slots. A slot whose in-flight chunks
        already cover its request's remaining tokens can be reassigned
        immediately: the old request keeps receiving from the chunk
        snapshots, the new request's prefill+insert are device-ordered
        after those chunks, and the next dispatched chunk serves the new
        occupant — admission overlaps the tail of the previous request
        instead of waiting out a fetch round trip."""
        steps = self._inflight_steps()
        out = []
        for i, r in enumerate(self._slot_req):
            if r is None:
                out.append(i)
            elif r.emitted + steps.get(i, 0) >= r.max_new_tokens or r.cancelled:
                out.append(i)
        return out

    def _any_active(self) -> bool:
        return any(r is not None for r in self._slot_req)

    def _needed_steps(self) -> int:
        """Decode steps still required to finish every current occupant,
        beyond what is already in flight — the dispatch gate. Bounds
        speculation by real demand (an upper bound under eos/cancel, which
        the host cannot project). A fresh occupant's un-fetched prefill
        entry is NOT extra demand: _inflight_steps counts the first token
        that entry carries, so `remaining` already discounts it."""
        steps = self._inflight_steps()
        worst = 0
        for i, r in enumerate(self._slot_req):
            if r is None or r.cancelled or not r.prefill_done:
                # a partial-prefill slot is resident but not decoding: its
                # demand starts when its last chunk activates it (counting
                # it here would dispatch decode chunks that only advance
                # garbage for it)
                continue
            remaining = r.max_new_tokens - r.emitted - steps.get(i, 0)
            if remaining > worst:
                worst = remaining
        return worst

    def _drain_and_observe(self, busy: bool) -> None:
        """Shared admission head (wave and chunked schedulers): drain the
        submit queue into the waiting list, shed requests past their TTFT
        deadline, flush queue-side terminations, refresh the state gauges."""
        while True:
            try:
                block = not busy and not self._waiting
                req = self._admit_q.get(timeout=0.05) if block else self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is None:
                self._stop = True
                break
            if req.cancelled:
                req.finish_reason = req.cancel_reason
                self._observe_finish(req, time.perf_counter())
                req.out.put(None)
                continue
            self._waiting.append(req)
        if self.ttft_deadline is not None and self._waiting:
            # shed-on-deadline: a request whose first token can no longer
            # arrive inside its TTFT budget gets a fast end-of-stream now
            # instead of consuming a prefill slot it can't benefit from
            now_t = time.perf_counter()
            kept = []
            for r in self._waiting:
                if (
                    r.submitted_at is not None
                    and now_t - r.submitted_at > self.ttft_deadline
                ):
                    self.shed += 1
                    r.finish_reason = "shed"
                    self._observe_finish(r, now_t)
                    r.out.put(None)
                else:
                    kept.append(r)
            self._waiting = kept
        self._expire_deadlines(time.perf_counter())
        self._order_waiting()
        # fresh pressure sample once per scheduler pass: brownout must be
        # able to DISENGAGE while no submits arrive (submit() feeds the
        # controller too, but an empty ingress would freeze the state)
        self.overload.observe(self.predicted_wait_s())
        if self.logger is not None:
            # queue-side terminations (cancelled in the drain, shed above)
            # have no collector iteration to flush them — do it here, on
            # the scheduler thread, with no lock held
            self._flush_wide_events()
        if self.metrics is not None:
            # engine-state gauges, refreshed once per scheduler pass —
            # lock-light sets, no device interaction
            active_n = sum(r is not None for r in self._slot_req)
            self.metrics.set_gauge(
                "app_llm_slots_in_use", float(active_n), model=self.label
            )
            self.metrics.set_gauge(
                "app_llm_queue_depth",
                float(self._admit_q.qsize() + len(self._waiting)),
                model=self.label,
            )
            self.metrics.set_gauge(
                "app_llm_admission_backlog", float(self._admitting),
                model=self.label,
            )
            self.metrics.set_gauge(
                "app_llm_brownout_state",
                1.0 if self.overload.brownout else 0.0, model=self.label,
            )
            if self.ledger is not None:
                self.metrics.set_gauge(
                    "app_llm_fairness_debt", self.ledger.debt_spread(),
                    model=self.label,
                )

    def _order_waiting(self) -> None:
        """Overload-aware queue order (replaces FIFO): interactive class
        first, then least weighted-served client (the fairness ledger's
        virtual token counter — "Fairness in Serving Large Language
        Models", OSDI'24), submit order last for determinism. Also
        refreshes the ledger's waiting-client set, which anchors the
        new-arrival floor. Sorting every pass is O(n log n) on a queue
        already bounded by max_queue; stable sort keeps equal keys FIFO."""
        led = self.ledger
        with self._lock:
            clients = {r.client for r in self._waiting}
            if led is not None:
                led.set_active(self.label, clients)
            if len(self._waiting) < 2:
                return
            # one bulk ledger snapshot for the whole sort: per-request
            # counter() calls would contend the fleet-shared lock
            # len(_waiting) times per scheduler pass per replica
            counters = led.counters_for(clients) if led is not None else {}
            self._waiting.sort(
                key=lambda r: (
                    1 if r.priority == "batch" else 0,
                    counters.get(r.client, 0.0),
                    r.id,
                )
            )

    def _preempt_for_waiting(self, free: list[int]) -> list[int]:
        """Priority preemption: when waiting interactive requests
        outnumber the free slots, take slots back from batch-class
        occupants — preferring the most recently admitted victim (least
        sunk progress to redo) — and return the refreshed free list.
        Nothing interactive waiting, or nothing batch slotted, is the
        common case and costs two scans of bounded lists."""
        if not self.preemption:
            return free
        with self._lock:
            want = sum(
                1 for r in self._waiting
                if r.priority != "batch" and r.finish_reason is None
            ) - len(free)
            if want <= 0:
                return free
            victims = [
                r for r in self._slot_req
                if r is not None and r.priority == "batch"
                and not r.cancelled and r.finish_reason is None
                # per-request preemption cap: a request evicted this many
                # times keeps its slot — without the bound, interactive
                # arrivals oscillating around capacity could thrash the
                # same batch request forever, re-running an ever-growing
                # continuation prefill at exactly the moment the engine
                # is pressured
                and r.preempted < self._PREEMPT_CAP
            ]
            if not victims:
                return free
            victims.sort(key=lambda r: (r.admitted_at or 0.0), reverse=True)
            for r in victims[:want]:
                self._preempt(r)
            self._kick.set()
            return self._free_slots()

    def _preempt(self, r: GenRequest) -> None:
        """Take r's slot back NOW: scrub every in-flight reference (no
        stale emission can reach it — the entry lists are shared with the
        collector, which only emits under this same lock), then fold the
        emitted tokens into the prompt and requeue as a continuation —
        the PR 5 failover re-seed, so a preempted greedy stream resumes
        token-identically; tokens computed-but-unfetched at preemption
        are recomputed by the continuation rather than emitted stale.
        Call with the lock held, scheduler thread only."""
        slot = r.slot
        if slot is not None and self._slot_req[slot] is r:
            self._slot_req[slot] = None
            if self.kv.paged:
                # the preempting request is about to seed this slot —
                # return the blocks now (in-flight programs targeting
                # them were dispatched earlier and execute before any
                # re-user's writes; single-device program order)
                self.kv.release_slot(slot, r)
                self._kv_hi[slot] = 0
        r.slot = None
        entries = list(self._inflight)
        if self._processing is not None:
            entries.append(self._processing)
        for e in entries:
            if e[0] == "prefill":
                # keep j-alignment with the first-token array: blank the
                # request, never remove the row
                e[2][:] = [
                    (s, rr if rr is not r else None) for s, rr in e[2]
                ]
            elif e[0] == "step":
                e[2][:] = [t for t in e[2] if t[2] is not r]
                if e[4] is not None:
                    for i, rr in enumerate(e[4]):
                        if rr is r:
                            e[4][i] = None
            elif e[0] == "verify":
                e[3][:] = [t for t in e[3] if t[1] is not r]
            else:
                for i, rr in enumerate(e[2]):
                    if rr is r:
                        e[2][i] = None
        try:
            self._prefilling.remove(r)
        except ValueError:
            pass
        # continuation re-seed (ReplicatedLLMEngine._failover semantics):
        # prompt grows by what was already streamed, scheduling state
        # resets, consumer-facing state (out queue, emitted) carries over
        # goodput replay marker: everything the continuation re-prefills
        # below this position was computed once already — the chunk
        # progress if nothing streamed yet, the whole grown prompt after
        # the history fold (the served tokens re-enter as prompt rows)
        replay_to = r.prefill_pos
        if r.history:
            r.prompt_tokens = list(r.prompt_tokens) + r.history
            r.history = []
            replay_to = len(r.prompt_tokens)
        r._replay_pos = max(r._replay_pos, replay_to)
        r.prefill_pos = 0
        r.prefill_done = False
        r._rows_hi = 0
        r._prefill_t0 = None
        r._spec_pending = []
        r._spec_inflight = 0
        r.phase = "queued"
        r.preempted += 1
        if self.tracer is not None and r.span is not None:
            # journey hop: the preemption continuation re-admits inside
            # this engine (it never passes through submit()), so the
            # continuation span is recorded here — linked to the original
            # request span, same trace, hop bumped (wide event reads
            # "hop N of journey J" across preemptions AND failovers)
            r.hop += 1
            t_ns = time.time_ns()
            self.tracer.record_span(
                "llm.continuation",
                trace_id=r.span.trace_id,
                parent_id=r.span.span_id,
                start_ns=t_ns, end_ns=t_ns,
                attributes={
                    "llm.model": self.label,
                    "llm.request_id": r.id,
                    "llm.hop": r.hop,
                    "llm.kind": "preemption",
                    "llm.preempted": r.preempted,
                    "llm.emitted": r.emitted,
                },
                links=[(r.span.trace_id, r.span.span_id)],
            )
        # fresh wait epoch, mirroring failover's path through submit():
        # without this, re-admission would observe queue_wait from the
        # ORIGINAL submit — service time + both waits in one inflated
        # sample, and the request counted twice in the histogram
        r.submitted_at = time.perf_counter()
        # outstanding-work estimate: the re-run prefill plus what decode
        # still owes (the residue of the old estimate is flushed)
        self._load_tokens -= r._load_acct
        r._load_acct = len(r.prompt_tokens) + max(
            0, r.max_new_tokens - r.emitted
        )
        self._load_tokens += r._load_acct
        self._waiting.append(r)
        if self.ledger is not None:
            self.ledger.touch(r.client)
        self.preemptions += 1
        if self.logger is not None:
            self.logger.info(
                f"preempted batch request {r.id} (emitted {r.emitted}); "
                "requeued as continuation"
            )
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_preemptions_total", model=self.label
            )

    def _expire_deadlines(self, now: float) -> None:
        """Retire every request whose wall deadline passed OR that was
        cancelled by its consumer — INCLUDING slotted ones.
        ttft_deadline_ms only sheds at admission; before this sweep a
        decode past its HTTP timeout kept burning chip time for a client
        that already hung up. The cancel half closes the same gap for
        disconnect-cancels: a cancelled occupant with an IDLE pipeline
        (nothing in flight to carry the finish through _emit_to) used to
        hold its slot and its consumer's end-of-stream until the next
        admission reassigned it. Retired occupants free their slot
        through the virtual-free path (in-flight snapshots drop their
        tokens), so the next admission reuses the slot immediately. Runs
        once per scheduler pass: O(slots + waiting), no device work."""
        deadline_hit = 0
        expired: list[tuple[GenRequest, str]] = []
        with self._lock:
            for slot, r in enumerate(self._slot_req):
                if r is None or r.finish_reason is not None:
                    continue
                if r.cancelled:
                    expired.append((r, r.cancel_reason))
                    self._slot_req[slot] = None
                elif r.deadline is not None and now > r.deadline:
                    expired.append((r, "deadline"))
                    self._slot_req[slot] = None
            if self._waiting:
                kept = []
                for r in self._waiting:
                    if r.finish_reason is not None:
                        continue  # closed elsewhere; drop from the queue
                    if r.cancelled:
                        expired.append((r, r.cancel_reason))
                    elif r.deadline is not None and now > r.deadline:
                        expired.append((r, "deadline"))
                    else:
                        kept.append(r)
                self._waiting = kept
            for r, reason in expired:
                r.cancelled = True  # in-flight snapshots drop its tokens
                r.finish_reason = reason
                if reason == "deadline":
                    self.deadline_cancels += 1
                    deadline_hit += 1
                self._observe_finish(r, now)
                r.out.put(None)
        if expired:
            self._kick.set()
            if deadline_hit and self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_deadline_cancels_total",
                    by=float(deadline_hit), model=self.label,
                )

    def _admit(self) -> bool:
        """Admission entry, called once per scheduler pass (THE seam:
        tests wedge it to freeze admission). Dispatches to the
        token-budget scheduler's immediate slot assignment or the
        monolithic path's wave batching."""
        return self._admit_chunked() if self.chunked else self._admit_wave()

    def _admit_wave(self) -> bool:
        """Pull waiting requests into (virtually) free slots, prefilling
        per bucket. Purely dispatch-side: decode chunks in flight are
        untouched, and the first sampled tokens merge into the device tail
        without a host round trip.

        Admission BATCHING: a prefill wave costs roughly the same device
        time at nb=1 as at nb=admit_cap, so firing a wave per trickle
        arrival melts throughput at mid load (measured open-loop: 200 QPS
        offered -> 138 achieved). While decode is active and a partial
        wave's oldest request is younger than admit_delay, hold admission
        to let the wave fill; an idle device admits immediately."""
        jnp = self._jnp
        with self._lock:
            free = self._free_slots()
            busy = self._any_active() or self._inflight or self._processing is not None
        self._drain_and_observe(busy)
        if self._waiting:
            free = self._preempt_for_waiting(free)
        if not self._waiting or not free:
            return False
        # Rate-gated wave-fill hold: a prefill wave costs device time that
        # barely depends on occupancy within a power-of-two width, so at
        # HIGH arrival rates it pays to wait (bounded by admit_delay) until
        # a meaningful wave accumulates. The gate (expected arrivals in the
        # window >= 4) keeps low-rate traffic on the admit-immediately
        # path: holding there adds chunk-pipeline slide (~2 chunks of
        # latency) and the wave never fills anyway.
        gap = self._ema_gap
        expected = self.admit_delay / gap if gap and gap > 0 else 0.0
        goal = min(self.admit_cap, int(expected))
        if (
            self.admit_delay > 0
            and busy
            and goal >= 4
            and len(self._waiting) < min(goal, len(free))
            and self._waiting[0].submitted_at is not None
            and time.perf_counter() - self._waiting[0].submitted_at < self.admit_delay
        ):
            return False
        pulled = self._waiting[: len(free)]
        self._waiting = self._waiting[len(free):]
        # visible to load() while in flight between _waiting and _slot_req —
        # without this the router undercounts a replica mid-admission and
        # least-loaded piles every request onto it
        self._admitting += len(pulled)
        # prefix consult: a hit skips its prefill wave entirely — the
        # retained KV rows and stored last-token logits go through the SAME
        # insert path as a prefilled wave (one _insert_many scatter + one
        # tail merge), so shared-prefix traffic costs no device prefill.
        # Contiguous layout: PrefixCache.lookup pins each entry until its
        # rows are inserted. Paged layout: the radix tree serves exact
        # hits (partials need the chunked scheduler's append path) and a
        # block RESERVATION gates admission — a pool that cannot host the
        # request's worst case keeps it queued instead of overcommitting.
        hits: list[tuple[GenRequest, Any]] = []
        misses: list[GenRequest] = pulled
        if self.kv.paged:
            # NOTE: no session restore here — the wave scheduler has no
            # mid-prompt append path, so a restored session could only
            # serve exact end records (which session publishes don't
            # store logits for); restoring would be pure wasted DMA +
            # pool churn. Sessions want the chunked scheduler.
            hits, misses, blocked = [], [], []
            for r in pulled:
                plan = self.kv.lookup_seed(r.prompt_tokens, allow_partial=False)
                r._kv_plan = plan
                if not self.kv.admit_reserve(
                    len(r.prompt_tokens), r.max_new_tokens, plan
                ):
                    self._kv_release_plan(r)
                    blocked.append(r)
                    continue
                r._kv_resv = self.kv.reserve_need(
                    len(r.prompt_tokens), r.max_new_tokens, plan
                )
                (hits.append((r, plan)) if plan is not None else misses.append(r))
            if blocked:
                with self._lock:
                    self._waiting = blocked + self._waiting
                    self._admitting -= len(blocked)
                pulled = [r for r in pulled if r not in blocked]
            if not pulled:
                return False
        elif self.kv.prefix is not None:
            hits, misses = [], []
            for r in pulled:
                e = self.kv.prefix.lookup(self.kv.prefix.key_for(r.prompt_tokens))
                (misses.append(r) if e is None else hits.append((r, e)))
        try:
            return self._admit_waves(hits, misses, free)
        except BaseException:
            self._requeue_stranded(pulled)
            raise
        finally:
            if self.kv.paged:
                # plans never attached (escaping device errors, groups
                # not reached) must drop their pins
                for r, _plan in hits:
                    self._kv_release_plan(r)

    def _admit_waves(
        self,
        hits: list[tuple[GenRequest, Any]],
        misses: list[GenRequest],
        free: list[int],
    ) -> bool:
        jnp = self._jnp
        self._fault("admission_oom")  # chaos seam: callers requeue stranded
        try:
            self._admit_exact_hits(hits, free)
        finally:
            # unpin EVERY looked-up entry in all paths — including the
            # groups never reached when an earlier group's device call
            # escapes to the scheduler's recovery. A pin that never drops
            # makes its entry uneviction-able forever. (Paged hits carry
            # SeedPlans, not pinned entries — radix mutation is
            # scheduler-thread-only, so nothing to release.)
            if self.kv.prefix is not None:
                for _, e in hits:
                    self.kv.prefix.release(e)
        # group by bucket to share prefill executions; chunks of admit_cap
        by_bucket: dict[int, list[GenRequest]] = {}
        for r in misses:
            by_bucket.setdefault(self._bucket_for(len(r.prompt_tokens)), []).append(r)
        by_wave: list[tuple[int, list[GenRequest]]] = []
        for bucket, reqs in by_bucket.items():
            for i in range(0, len(reqs), self.admit_cap):
                by_wave.append((bucket, reqs[i : i + self.admit_cap]))
        for bucket, reqs in by_wave:
            nb = self._wave_width(len(reqs))
            pack = np.zeros((nb, bucket + 2), np.int32)
            pack[:, -2] = 1  # pad rows: 1 token, discarded
            for j, r in enumerate(reqs):
                n = len(r.prompt_tokens)
                pack[j, :n] = r.prompt_tokens
                pack[j, -2] = n
                pack[j, -1] = np.float32(r.temperature).view(np.int32)
            t0 = time.perf_counter()
            with self._hb_dispatch.beat("dispatch:prefill"):
                first_dev, new_cache, logits_dev, self._rng = self._prefill_op(
                    self.params, jnp.asarray(pack), self._rng,
                )
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_tpu_stats", time.perf_counter() - t0,
                    model="llm", op=f"prefill_dispatch_{bucket}",
                )
            if self.kv.prefix is not None:
                # retain each fresh row + its last-token logits for future
                # hits; device-side slices, refcount/LRU inside the cache.
                # Rows are TRIMMED to the wave's bucket (valid rows never
                # exceed it — dense slabs are capacity-wide and mostly pad
                # at short buckets, so storing them whole would spend the
                # byte budget capacity/bucket-fold on padding); assemble()
                # pads back to capacity at hit time.
                keep = min(bucket, self.kv.capacity)
                for j, r in enumerate(reqs):
                    self.kv.prefix.put(
                        self.kv.prefix.key_for(r.prompt_tokens),
                        new_cache.k[:, j : j + 1, :keep],
                        new_cache.v[:, j : j + 1, :keep],
                        len(r.prompt_tokens), logits_dev[j : j + 1],
                    )
            self._slot_in(
                reqs, first_dev, new_cache, free,
                wave_nb=nb, wave_t0=t0, bucket=bucket,
            )
            if self.kv.paged and self.kv.share:
                # publish AFTER the insert (paged publishing shares the
                # SLOT's resident blocks in place — they must hold the
                # rows first); the contiguous path published the wave's
                # own rows pre-insert above
                for j, r in enumerate(reqs):
                    if r.slot is not None and self._slot_req[r.slot] is r:
                        self._kv_publish(
                            r.slot, r,
                            None if logits_dev is None else logits_dev[j : j + 1],
                        )
        return True

    def _admit_exact_hits(
        self, hits: list[tuple[GenRequest, Any]], free: list[int]
    ) -> None:
        """Dispatch exact prefix-cache hits (both schedulers share this):
        per admit_cap group, assemble the pinned entries' rows into one
        insert wave, re-sample each request's first token from the stored
        last-token logits at its own temperature, and slot the group in.
        Callers own the pins — their finally releases EVERY looked-up
        entry, including groups never reached when a device call escapes
        to the scheduler's recovery."""
        if self.kv.paged:
            return self._admit_exact_hits_paged(hits, free)
        jnp = self._jnp
        for i in range(0, len(hits), self.admit_cap):
            group = hits[i : i + self.admit_cap]
            reqs = [r for r, _ in group]
            nb = self._wave_width(len(reqs))
            t0 = time.perf_counter()
            new_cache, logits = self.kv.prefix.assemble(
                [e for _, e in group], nb, self.kv.capacity
            )
            temps = np.zeros((nb,), np.float32)
            temps[: len(reqs)] = [r.temperature for r in reqs]
            first_dev, self._rng = self._hit_first_op(
                logits, jnp.asarray(temps), self._rng
            )
            for r in reqs:
                r.prefix_hit = True
            self._slot_in(reqs, first_dev, new_cache, free, wave_t0=t0)

    def _admit_exact_hits_paged(
        self, hits: list[tuple[GenRequest, Any]], free: list[int]
    ) -> None:
        """Paged exact hits: NO KV rows move for the shared prefix — the
        slot's block table points at the radix blocks in place
        (refcount++); only the sub-block tail is block-copied (COW by
        construction) and the first token re-samples from the stored
        last-token logits, exactly the PrefixCache exact-hit contract."""
        jnp = self._jnp
        M = self.admit_cap
        for i in range(0, len(hits), M):
            group = hits[i : i + M]
            reqs = [r for r, _ in group]
            nb = self._wave_width(len(reqs))
            t0 = time.perf_counter()
            rows = [p.logits for _, p in group]
            rows += [rows[0]] * (nb - len(group))
            logits = jnp.concatenate(rows, axis=0)
            temps = np.zeros((nb,), np.float32)
            temps[: len(reqs)] = [r.temperature for r in reqs]
            first_dev, self._rng = self._hit_first_op(
                logits, jnp.asarray(temps), self._rng
            )
            now = time.perf_counter()
            for r in reqs:
                self._observe_admission(r, now)
            oob_b = self.kv.pool.n_blocks
            with self._work_cv:
                srcs = np.full((M,), oob_b, np.int32)
                dsts = np.full((M,), oob_b, np.int32)
                slot_idx = np.full((M,), self.slots, np.int32)
                lens = np.zeros((M,), np.int32)
                meta = np.zeros((3, M), np.int32)
                taken: list[tuple[int, GenRequest]] = []
                for j, (r, plan) in enumerate(group):
                    slot = free.pop(0)
                    self._assign_slot(r, slot, now)
                    info = self._kv_attach(r, slot, plan)
                    taken.append((slot, r))
                    r.prefix_hit = True
                    r.prefill_pos = len(r.prompt_tokens)
                    r.prefill_done = True
                    self._load_credit(r, len(r.prompt_tokens))
                    for s_, d_ in info["copies"]:
                        srcs[j], dsts[j] = s_, d_
                    slot_idx[j] = slot
                    lens[j] = info["seed_len"]
                    meta[0, j], meta[1, j] = slot, j
                    meta[2, j] = np.float32(r.temperature).view(np.int32)
                for j in range(len(group), M):
                    meta[:, j] = meta[:, 0]
                self.cache, self._kv_scales = self._seed_op(
                    self.cache, self._kv_scales,
                    jnp.asarray(srcs), jnp.asarray(dsts),
                    jnp.asarray(slot_idx), jnp.asarray(lens),
                )
                md = jnp.asarray(meta)
                self._tail, self._active, self._temps = self._admit_update(
                    self._tail, self._active, self._temps, first_dev, md
                )
                self._start_fetch(first_dev)
                self._inflight.append((
                    "prefill", first_dev, taken,
                    {"t0": t0, "nb": 0, "bucket": None},
                ))
                self._admitting -= len(reqs)
                self._work_cv.notify()

    def _requeue_stranded(self, pulled: list[GenRequest]) -> None:
        """An escaping admission error strands requests already sliced out
        of _waiting but never slotted: they appear in no in-flight entry
        and own no slot, so _recover_all/_close_unreachable walk right
        past them and their consumers would hang until the stream timeout.
        Put exactly those back at the head of _waiting — recovery leaves
        the queue intact, so the next scheduler pass retries them (and
        _die's drain closes them if the engine is lost). Slotted members
        of a failed group stay out: _abort_all reaches them via the slot
        table."""
        with self._lock:
            stranded = [
                r for r in pulled
                if r.finish_reason is None
                and (r.slot is None or self._slot_req[r.slot] is not r)
            ]
            self._waiting = stranded + self._waiting
            self._admitting -= len(stranded)
        if self.kv.paged:
            # hand unconsumed block promises and plan pins back: a
            # reservation/pin whose request re-queued would otherwise
            # shrink the pool forever
            for r in stranded:
                if r._kv_resv:
                    self.kv.unreserve(r._kv_resv)
                    r._kv_resv = 0
                self._kv_release_plan(r)

    def _observe_admission(self, r: GenRequest, now: float) -> None:
        """queue_wait closes at admission (slot assigned, KV en route)."""
        r.admitted_at = now
        r.phase = "prefill"
        if self.ledger is not None and not r._prompt_billed:
            # prompt tokens bill once per request lifetime: a preempted
            # or failed-over continuation re-prefills its (grown) prompt,
            # but double-billing it would punish the client for the
            # engine's own scheduling decision
            r._prompt_billed = True
            self.ledger.charge(r.client, len(r.prompt_tokens))
        if r.submitted_at is not None:
            wait = now - r.submitted_at
            self._phases["queue_wait"].observe(wait)
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_llm_queue_wait_seconds", wait, model=self.label,
                    exemplar=(
                        {"trace_id": r.span.trace_id}
                        if r.span is not None else None
                    ),
                    **self._role_labels,
                )
            self._phase_span(r, "llm.queue_wait", r.submitted_at, now)

    def _assign_slot(self, r: GenRequest, slot: int, now: float) -> None:
        """Make r the slot's occupant (call with the lock held). A
        cancelled previous occupant may have no in-flight snapshot left
        to deliver its end-of-stream — close it here (same contract as
        the wave path's _slot_in)."""
        old = self._slot_req[slot]
        if old is not None and old.cancelled and old.finish_reason is None:
            old.finish_reason = old.cancel_reason
            self._observe_finish(old, now)
            old.out.put(None)
        self._slot_req[slot] = r
        r.slot = slot
        if self.lora_slots and self._aids_host[slot] != r._aid:
            # the slot's lane now computes under r's adapter; the device
            # mirror re-ships lazily at the next dispatch (_ship_aids)
            self._aids_host[slot] = r._aid
            self._aids_dirty = True

    def _ship_aids(self) -> None:
        """Re-ship the per-slot adapter-id vector into the params pytree
        when slot assignments changed (SCHEDULER THREAD ONLY — dispatches
        follow immediately). One tiny [slots] int32 h2d per assignment
        batch, not per dispatch: the tables inside params are untouched
        and params is never donated, so this is a dict rebuild around the
        same device buffers and every jit cache stays warm."""
        if not self.lora_slots or not self._aids_dirty:
            return
        with self._lock:
            host = np.asarray(self._aids_host, np.int32)
            self._aids_dirty = False
        if self._sharded:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            aids = self._jax.device_put(
                host, NamedSharding(self.mesh, _P(None))
            )
        elif self.device is not None:
            aids = self._jax.device_put(host, self.device)
        else:
            aids = self._jax.device_put(host)
        self.params = {**self.params, "aids": aids}

    # -- paged-pool plumbing (kvcache.paged; SCHEDULER THREAD ONLY — the
    # helpers below dispatch device work against the donated pool) -------
    def _tables_device(self):
        """Device mirror of the block tables, re-shipped only when the
        host bookkeeping changed (one small h2d per table mutation, not
        per dispatch)."""
        t = self.kv.take_tables()
        if t is not None:
            self._tables_dev = self._jnp.asarray(t)
        return self._tables_dev

    def _kv_attach(self, r: GenRequest, slot: int, plan) -> dict:
        """Bind a slot's block table to its (possibly shared) seed plan;
        releases the previous occupant's blocks in the same move. The
        plan's lookup-time pins transfer to the slot (attach_seed)."""
        plen = len(r.prompt_tokens)
        info = self.kv.attach_seed(slot, plan, r, plen, r.max_new_tokens)
        r._kv_limit = self.kv.reserve_tokens(plen, r.max_new_tokens)
        r._kv_resv = 0  # admission promise consumed (now on the slot)
        r._kv_plan = None  # pins adopted by the slot table
        self._kv_hi[slot] = info["seed_len"]
        return info

    def _kv_release_plan(self, r: GenRequest) -> None:
        """Drop an unconsumed seed plan's pins (blocked requeues,
        stranded admissions, groups never reached after an escaping
        device error). Idempotent — attach clears the plan."""
        plan = r._kv_plan
        if plan is not None:
            r._kv_plan = None
            self.kv.release_plan(plan)

    def _kv_publish(self, slot: int, r: GenRequest, logits_dev=None, *,
                    session: bool = False) -> None:
        """Publish a slot's resident prefix into the radix tree: full
        blocks shared in place (refcount++), the sub-block tail COPIED
        into a radix-owned block (one tiny device dispatch), last-token
        logits retained for exact hits. session=True publishes the whole
        conversation (prompt + emitted) and pins it to the session id."""
        if not self.kv.paged or self.kv.radix is None:
            return
        if r._aid != 0:
            # adapted lanes never publish: their K/V rows were computed
            # under THIS tenant's wq/wkv deltas, so sharing them through
            # the radix tree would seed other tenants (or the base) with
            # prefix state from the wrong weights
            return
        # session publishes drop the LAST emitted token: a sampled token's
        # K/V row is only written when it re-enters as the next step's
        # input, so the final token of a finished stream has no resident
        # row — the next turn re-prefills it along with the new text
        tokens = r.prompt_tokens + (r.history[:-1] if session else [])
        if not tokens:
            return
        plan = self.kv.publish_plan(slot, tokens, want_tail=True)
        if plan is None:
            return
        jnp = self._jnp
        if plan["tail_dst"] >= 0:
            # padded to the SAME (admit_cap,) shape the exact-hit seeds
            # and warmup use — a (1,)-shaped variant would compile a
            # fresh executable on the scheduler thread at the first
            # publish, mid-serving (pad lanes: src clipped, dst/slot
            # out of bounds -> dropped)
            M = self.admit_cap
            oob_b = self.kv.pool.n_blocks
            srcs = np.full((M,), oob_b, np.int32)
            dsts = np.full((M,), oob_b, np.int32)
            srcs[0], dsts[0] = plan["tail_src"], plan["tail_dst"]
            self.cache, self._kv_scales = self._seed_op(
                self.cache, self._kv_scales,
                jnp.asarray(srcs), jnp.asarray(dsts),
                jnp.full((M,), self.slots, jnp.int32),  # no length change
                jnp.zeros((M,), jnp.int32),
            )
        self.kv.publish_commit(
            plan, tokens, logits=logits_dev,
            logits_nbytes=(0 if logits_dev is None else int(logits_dev.nbytes)),
            session_id=(r.session_id if session else None),
        )

    def _kv_session_flush(self) -> None:
        """Process end-of-turn session publishes the collector deferred
        (only the scheduler may dispatch against the donated pool). Slot
        ownership is re-checked: under slot pressure a reassigned slot's
        publish is skipped — the session goes cold, never corrupt."""
        while self._session_pub:
            slot, r = self._session_pub.popleft()
            if self.kv.slot_owner(slot) is r and not r._session_published:
                self._kv_publish(slot, r, None, session=True)
            r._session_published = True

    def _kv_sweep(self) -> None:
        """Return retired occupants' blocks to the pool. Runs after the
        session flush so an end-of-turn publish still sees its blocks;
        finished session turns awaiting their publish keep them one more
        pass."""
        for i in range(self.slots):
            r = self.kv.slot_owner(i)
            if not isinstance(r, GenRequest):
                continue
            if r.finish_reason is None or r.finish_reason == "failover":
                continue
            if (
                r.session_id and not r._session_published
                and r.finish_reason in ("eos", "length")
            ):
                continue
            cur = self._slot_req[i]
            if cur is None or cur is r:
                self.kv.release_slot(i, r)
                self._kv_hi[i] = 0

    def _kv_session_spill(self) -> None:
        """LRU-spill cold sessions' blocks to the host tier when their
        device budget is exceeded: fetch the blocks (d2h), hand them to
        the offload store, release the device copies."""
        if not self.kv.paged or self.kv.sessions is None:
            return
        cands = self.kv.spill_candidates()
        if not cands:
            return
        from .kvcache.paged import gather_blocks_host

        for s in cands:
            path = self.kv.session_path(s.id)
            if path is None:
                continue
            blocks = list(path["blocks"])
            if path["tail"] >= 0:
                blocks.append(path["tail"])
            if not blocks:
                continue
            sc = self._kv_scales if self.kv.int8 else None
            k, v, scales = gather_blocks_host(
                self.cache.k, self.cache.v, blocks, scales=sc
            )
            payload = {
                "tokens": path["tokens"], "k": k, "v": v, "sc": scales,
                "n_full": len(path["blocks"]), "tail_len": path["tail_len"],
            }
            nbytes = k.nbytes + v.nbytes + (
                scales.nbytes if scales is not None else 0
            )
            self.kv.spill_commit(s.id, payload, nbytes)

    def _session_prepare(self, sid: str) -> None:
        """Admission-side session touch: a spilled conversation is
        restored block-wise (h2d into fresh pool blocks, re-inserted
        into the radix) BEFORE the radix consult, so the next turn's
        prompt block-shares the whole history. A pool too tight to
        restore leaves the session cold — full re-prefill, never an
        error."""
        if not self.kv.paged or self.kv.sessions is None or not sid:
            return
        if self.kv.session_touch(sid) != "spilled":
            return
        payload = self.kv.restore_fetch(sid)
        if payload is None or payload.get("k") is None:
            return
        n = int(payload["k"].shape[1])
        ids = self.kv.alloc_restore(n)
        if ids is None:
            # the payload is consumed and the pool cannot host it: drop
            # the session cleanly (a "spilled" entry with no payload
            # would leak in the registry and dead-end every later turn)
            self.kv.session_forget(sid)
            return
        self._kv_restore_blocks(
            payload["k"], payload["v"], payload.get("sc"), ids
        )
        n_full = int(payload["n_full"])
        tail_block = ids[n_full] if n > n_full else -1
        self.kv.restore_commit(
            sid, payload["tokens"], ids[:n_full], tail_block,
            int(payload["tail_len"]),
        )

    def _kv_restore_blocks(self, k, v, sc, ids: list[int]) -> None:
        """Scatter block payloads (host numpy from a session spill, or
        arrays a KV handoff placed on this engine's device) into freshly
        allocated pool blocks through the padded restore-op family.
        SCHEDULER THREAD ONLY — the restore op donates the pool."""
        jnp = self._jnp
        n = len(ids)
        width = 1 << max(0, n - 1).bit_length()  # pow-2 compile shapes
        op = self._restore_ops.get(width)
        if op is None:
            from .profiling import instrument_jit

            op = instrument_jit(
                f"llm.kv_restore{width}", self._restore_base,
                model=self.label, metrics=self.metrics,
                donate_argnums=((0, 1) if self.kv.int8 else (0,)),
            )
            self._restore_ops[width] = op
        pad = width - n

        def padd(a, axis):
            a = jnp.asarray(a)
            if pad == 0:
                return a
            pw = [(0, 0)] * a.ndim
            pw[axis] = (0, pad)
            return jnp.pad(a, pw)

        hk = padd(k, 1)
        hv = padd(v, 1)
        hs = (
            padd(sc, 2) if self.kv.int8
            else jnp.zeros((0,), jnp.float32)
        )
        dsts = jnp.asarray(
            np.asarray(ids + [self.kv.pool.n_blocks] * pad, np.int32)
        )
        with self._work_cv:
            self.cache, self._kv_scales = op(
                self.cache, self._kv_scales, hk, hv, hs, dsts
            )

    # -- scheduler-thread host work (KV handoff; disaggregated serving) --
    def _run_sched_work(self) -> None:
        """Run host-work closures other threads queued for the scheduler
        (the only thread allowed to dispatch against the donated pool
        arrays). A closure's error lands in its caller's box — it must
        never kill the engine loop."""
        while self._sched_work:
            try:
                fn, box = self._sched_work.popleft()
            except IndexError:  # racing _die's drain
                break
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001 — caller's error, not ours
                box["error"] = e
            finally:
                box["done"].set()

    def _fail_sched_work(self) -> None:
        """End every queued scheduler-work box (engine dying/closing) so
        handoff callers fail fast instead of riding out their timeout."""
        while self._sched_work:
            try:
                _fn, box = self._sched_work.popleft()
            except IndexError:
                break
            box["error"] = EngineStoppedError("engine stopped")
            box["done"].set()

    def _run_on_scheduler(self, fn, timeout: float | None = None):
        if not self.alive():
            raise EngineStoppedError("engine stopped")
        box: dict = {"done": threading.Event(), "result": None, "error": None}
        self._sched_work.append((fn, box))
        self._kick.set()
        if not self.alive():
            # raced _die/close past the check above: their one-shot
            # _fail_sched_work may already have drained the deque before
            # our append, so nothing would ever pop this box — drain it
            # ourselves and fail fast instead of riding out the timeout
            self._fail_sched_work()
        wait_s = timeout if timeout is not None else 30.0
        if not box["done"].wait(wait_s):
            raise TimeoutError(
                f"scheduler work timed out after {wait_s}s (engine "
                f"{'alive' if self.alive() else 'dead'})"
            )
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def kv_placement(self):
        """Where this engine's pool arrays live — the ``jax.device_put``
        target for a direct device-to-device KV handoff (the committed
        replica device, or the submesh NamedSharding of a TP engine).
        None = unpinned default placement; handoff callers host-stage."""
        if self._kv_sharding is not None:
            return self._kv_sharding
        return self.device

    def kv_handoff_export(
        self, prompt_tokens: list[int], *, timeout: float | None = None,
    ) -> dict | None:
        """Gather one exact published prompt's KV blocks plus its stored
        last-token logits for a prefill->decode handoff
        (docs/advanced-guide/sharded-serving.md#disaggregation). Returns
        the payload the peer's :meth:`kv_handoff_import` consumes —
        device arrays, so the caller chooses d2d ``jax.device_put`` or
        byte-identical host staging — or None when the prompt is not an
        exact published record (dropped publish, evicted, sharing off).
        Runs on the scheduler thread (the pool arrays are donated)."""
        if not self.kv.paged or self.kv.radix is None:
            return None
        jnp = self._jnp

        def work():
            t0 = time.perf_counter()
            plan = self.kv.lookup_seed(
                list(prompt_tokens), allow_partial=False, count=False
            )
            if plan is None or not plan.exact or plan.logits is None:
                if plan is not None:
                    self.kv.release_plan(plan)
                return None
            try:
                blocks = list(plan.blocks)
                tail = int(plan.tail_src)
                all_blocks = blocks + ([tail] if tail >= 0 else [])
                if not all_blocks:
                    return None
                idx = jnp.asarray(np.asarray(all_blocks, np.int32))
                k = jnp.take(self.cache.k, idx, axis=1)
                v = jnp.take(self.cache.v, idx, axis=1)
                sc = (
                    jnp.take(self._kv_scales, idx, axis=2)
                    if self.kv.int8 else None
                )
                if self.metrics is not None:
                    self.metrics.record_histogram(
                        "app_llm_collective_seconds",
                        time.perf_counter() - t0,
                        model=self.label, phase="kv_handoff_gather",
                    )
                return {
                    "tokens": list(prompt_tokens),
                    "k": k, "v": v, "sc": sc,
                    "n_full": len(blocks),
                    "tail_len": int(plan.tail_len) if tail >= 0 else 0,
                    "logits": plan.logits,
                }
            finally:
                self.kv.release_plan(plan)

        return self._run_on_scheduler(work, timeout)

    def kv_handoff_import(
        self, payload: dict, *, timeout: float | None = None,
    ) -> bool:
        """Adopt a peer's exported prompt KV: allocate pool blocks,
        scatter the payload in (byte-identical — the restore-op family),
        and publish the prompt into the radix WITH its last-token
        logits, so this engine's next admission of that prompt is an
        exact hit that skips prefill entirely (the disaggregated decode
        contract). False = the pool cannot host it right now — the
        caller submits anyway and the engine re-prefills (slower, never
        wrong). Runs on the scheduler thread."""
        if not self.kv.paged or self.kv.radix is None:
            return False

        def work():
            t0 = time.perf_counter()
            k = payload["k"]
            n = int(k.shape[1])
            ids = self.kv.alloc_restore(n)
            if ids is None:
                return False
            try:
                self._kv_restore_blocks(k, payload["v"], payload.get("sc"), ids)
            except BaseException:
                self.kv.release_blocks(ids)
                raise
            n_full = int(payload["n_full"])
            tail_block = ids[n_full] if n > n_full else -1
            logits = payload.get("logits")
            logits_dev = None if logits is None else self._jnp.asarray(logits)
            self.kv.handoff_commit(
                payload["tokens"], ids[:n_full], tail_block,
                int(payload["tail_len"]),
                logits=logits_dev,
                logits_nbytes=(
                    0 if logits_dev is None else int(logits_dev.nbytes)
                ),
            )
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_llm_collective_seconds",
                    time.perf_counter() - t0,
                    model=self.label, phase="kv_handoff_scatter",
                )
            return True

        return self._run_on_scheduler(work, timeout)

    def _admit_chunked(self) -> bool:
        """Chunked-scheduler admission: assign waiting requests to
        (virtually) free slots IMMEDIATELY — no wave-fill hold, because
        per-step packing replaces wave batching — and classify each
        against the prefix cache: an exact hit skips prefill entirely
        (stored last-token logits, the wave path's machinery); a partial
        hit seeds the slot's KV with the shared prefix and starts the
        prefill cursor mid-prompt; a miss starts at 0. Misses and
        partials do no prefill compute here — their chunks are packed
        into unified steps by _dispatch_step."""
        jnp = self._jnp
        with self._lock:
            free = self._free_slots()
            busy = (
                self._any_active() or bool(self._prefilling)
                or bool(self._inflight) or self._processing is not None
            )
        self._drain_and_observe(busy)
        if self._waiting:
            free = self._preempt_for_waiting(free)
        if not self._waiting or not free:
            return False
        self._fault("admission_oom")  # chaos seam: nothing pulled yet
        pulled = self._waiting[: len(free)]
        self._waiting = self._waiting[len(free):]
        self._admitting += len(pulled)
        hits: list[tuple[GenRequest, Any]] = []
        partials: list[tuple[GenRequest, Any]] = []
        rest: list[GenRequest] = pulled
        if self.kv.paged:
            # radix consult at BLOCK granularity: exact end records skip
            # prefill entirely; any block-aligned shared prefix seeds the
            # slot mid-prompt (the generalization of lookup_longest —
            # sibling prompts share every common block, not just stored
            # whole rows). The block reservation gates admission: a pool
            # that cannot host a request keeps it queued.
            rest, blocked = [], []
            for r in pulled:
                if r.session_id:
                    self._session_prepare(r.session_id)
                # constrained requests force a radix MISS: an exact hit
                # admits through _hit_first, a program the grammar mask
                # does not ride — re-prefilling trades latency for the
                # validity guarantee (partial seeds would be fine, but
                # one rule is auditable). Adapted requests (gofr_tpu.lora)
                # also force a miss: shared radix blocks hold K/V computed
                # under the BASE wq/wkv, not this tenant's deltas.
                plan = (
                    self.kv.lookup_seed(r.prompt_tokens)
                    if self.kv.share and r.grammar is None and r._aid == 0
                    else None
                )
                r._kv_plan = plan
                if not self.kv.admit_reserve(
                    len(r.prompt_tokens), r.max_new_tokens, plan
                ):
                    self._kv_release_plan(r)
                    blocked.append(r)
                    continue
                r._kv_resv = self.kv.reserve_need(
                    len(r.prompt_tokens), r.max_new_tokens, plan
                )
                if plan is None:
                    rest.append(r)
                elif plan.exact:
                    hits.append((r, plan))
                else:
                    partials.append((r, plan))
            if blocked:
                with self._lock:
                    self._waiting = blocked + self._waiting
                    self._admitting -= len(blocked)
                pulled = [r for r in pulled if r not in blocked]
            if not pulled:
                return False
        elif self.kv.prefix is not None:
            rest = []
            for r in pulled:
                if r.grammar is not None or r._aid != 0:
                    rest.append(r)  # constrained/adapted: full prefill
                    continue
                # mid-prompt seeding is a dense-layout move: a rolling
                # entry's ring rows are laid out for ITS final length and
                # cannot serve a shorter prefix — the cache skips the
                # partial probe entirely (no pin/LRU-bump/counter for
                # hits we would discard)
                e, exact = self.kv.prefix.lookup_longest(
                    r.prompt_tokens, allow_partial=not self.kv.rolling
                )
                if e is None:
                    rest.append(r)
                elif exact:
                    hits.append((r, e))
                else:
                    partials.append((r, e))
        try:
            # exact hits ride the wave path's machinery unchanged: stored
            # logits -> first token, rows -> insert_many (contiguous) or
            # table seeding (paged), slot activated
            self._admit_exact_hits(hits, free)
            # partial hits: seed the shared prefix, start the prefill
            # cursor mid-prompt, remaining chunks run through unified steps
            now = time.perf_counter()
            if self.kv.paged:
                # block-granular seeding is pure table bookkeeping: the
                # slot's table points at the shared radix blocks in
                # place — ZERO device work; the first append's pack
                # carries the cursor, so even lengths need no scatter
                with self._work_cv:
                    for r, plan in partials:
                        slot = free.pop(0)
                        self._assign_slot(r, slot, now)
                        self._kv_attach(r, slot, plan)
                        r.prefix_hit = True
                        r.prefill_pos = plan.shared
                        r._rows_hi = plan.shared
                        self._load_credit(r, plan.shared)
                        self._observe_admission(r, now)
                        self._prefilling.append(r)
                    self._admitting -= len(partials)
            else:
                for i in range(0, len(partials), self.admit_cap):
                    group = partials[i : i + self.admit_cap]
                    nb = self._wave_width(len(group))
                    new_cache, _logits = self.kv.prefix.assemble(
                        [e for _, e in group], nb, self.kv.capacity
                    )
                    with self._work_cv:
                        meta = np.zeros((3, self.admit_cap), np.int32)
                        for j, (r, e) in enumerate(group):
                            slot = free.pop(0)
                            self._assign_slot(r, slot, now)
                            r.prefix_hit = True
                            r.prefill_pos = e.length
                            r._rows_hi = e.length
                            self._load_credit(r, e.length)
                            meta[0, j], meta[1, j] = slot, j
                        for j in range(len(group), self.admit_cap):
                            meta[:, j] = meta[:, 0]
                        self.cache = self._insert_many(
                            self.cache, new_cache, jnp.asarray(meta)
                        )
                        for r, _e in group:
                            self._observe_admission(r, now)
                            self._prefilling.append(r)
                        self._admitting -= len(group)
        except BaseException:
            # pulled-but-unslotted requests (later groups, the whole miss
            # list) are otherwise unreachable from recovery — see
            # _requeue_stranded
            self._requeue_stranded(pulled)
            raise
        finally:
            # unpin EVERY looked-up entry/plan in all paths — including
            # groups never reached when an earlier group's device call
            # escapes to the scheduler's recovery. A pin that never
            # drops makes its entry uneviction-able (contiguous) or
            # leaks pool refs (paged).
            if self.kv.prefix is not None:
                for _r, e in hits:
                    self.kv.prefix.release(e)
                for _r, e in partials:
                    self.kv.prefix.release(e)
            elif self.kv.paged:
                for r, _plan in hits:
                    self._kv_release_plan(r)
                for r, _plan in partials:
                    self._kv_release_plan(r)
        # misses: slot residency only; chunks flow through unified steps
        if rest:
            now = time.perf_counter()
            with self._work_cv:
                for r in rest:
                    slot = free.pop(0)
                    self._assign_slot(r, slot, now)
                    if self.kv.paged:
                        self._kv_attach(r, slot, None)
                    self._observe_admission(r, now)
                    self._prefilling.append(r)
                self._admitting -= len(rest)
        self._kick.set()
        return True

    def _slot_in(
        self,
        reqs: list[GenRequest],
        first_dev,
        new_cache,
        free: list[int],
        wave_nb: int | None = None,
        wave_t0: float | None = None,
        bucket: int | None = None,
    ) -> None:
        """Shared admission tail for prefilled waves and prefix-cache hit
        waves: copy KV rows into (virtually) free slots via ONE jitted
        insert-many, scatter first tokens into the on-device chain tail,
        and queue the entry for the collector. wave_nb records prefill wave
        width telemetry (hit waves dispatched no prefill, so they don't);
        wave_t0/bucket feed the prefill phase span recorded at fetch."""
        jnp = self._jnp
        now = time.perf_counter()
        for r in reqs:
            self._observe_admission(r, now)
        info = {
            "t0": wave_t0 if wave_t0 is not None else now,
            "nb": wave_nb or 0,
            "bucket": bucket,
        }
        with self._work_cv:
            meta = np.zeros((3, self.admit_cap), np.int32)
            taken: list[tuple[int, GenRequest]] = []
            for j, r in enumerate(reqs):
                slot = free.pop(0)
                self._assign_slot(r, slot, now)
                taken.append((slot, r))
                # wave admission covers the whole prompt in one dispatch
                r.prefill_pos = len(r.prompt_tokens)
                r.prefill_done = True
                self._load_credit(r, len(r.prompt_tokens))
                if self.kv.paged:
                    # bind the table + materialize blocks for the prompt
                    # rows the insert scatter is about to write
                    self._kv_attach(r, slot, None)
                    self.kv.ensure(slot, len(r.prompt_tokens))
                    self._kv_hi[slot] = len(r.prompt_tokens)
                meta[0, j], meta[1, j] = slot, j
                meta[2, j] = np.float32(r.temperature).view(np.int32)
            # pad entries duplicate entry 0 (idempotent)
            for j in range(len(reqs), self.admit_cap):
                meta[:, j] = meta[:, 0]
            md = jnp.asarray(meta)  # ONE packed h2d per wave
            if self.kv.paged:
                self.cache, self._kv_scales = self._insert_paged_op(
                    self.cache, self._kv_scales, new_cache, md[:2],
                    self._tables_device(),
                )
            else:
                self.cache = self._insert_many(self.cache, new_cache, md)
            self._tail, self._active, self._temps = self._admit_update(
                self._tail, self._active, self._temps, first_dev, md
            )
            self._start_fetch(first_dev)
            self._inflight.append(("prefill", first_dev, taken, info))
            self._admitting -= len(reqs)
            if wave_nb is not None:
                # under the lock: stats() iterates _stat_waves concurrently
                self._stat_waves[wave_nb] = self._stat_waves.get(wave_nb, 0) + 1
                self._stat_wave_reqs += len(reqs)
            self._work_cv.notify()

    @staticmethod
    def _start_fetch(arr) -> None:
        copy = getattr(arr, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # pragma: no cover — backend-dependent
                pass

    # -- observability ----------------------------------------------------
    def _observe_mfu(
        self, phase: str, tokens: int, flops: float, bytes_moved: float, dt: float,
    ) -> None:
        """One MFU/roofline observation for a finished device window.
        dt is the dispatch->fetch wall interval; decode chunks PIPELINE
        (up to `lookahead` in flight), so overlapping windows make this
        an apparent utilization — read the window percentiles, never sum
        them. Gauges carry the latest value; the rolling windows feed
        stats()/debug/bench."""
        if dt <= 0 or flops <= 0:
            return
        mfu = flops / dt / (self._peak_flops * self._n_chips)
        ratio = self._mfu_mod.roofline_ratio(
            flops, bytes_moved, self._peak_flops * self._n_chips,
            self._hbm_bw * self._n_chips,
        )
        self._mfu_windows[phase].observe(mfu)
        self._roofline_windows[phase].observe(ratio)
        if phase == "decode":
            self._tok_chip_window.observe(tokens / dt / self._n_chips)
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_mfu", mfu, model=self.label, phase=phase
            )
            self.metrics.set_gauge(
                "app_llm_roofline_ratio", ratio, model=self.label, phase=phase
            )
            if phase == "decode":
                self.metrics.set_gauge(
                    "app_llm_tokens_per_second_per_chip",
                    tokens / dt / self._n_chips, model=self.label,
                )

    def _mfu_summary(self) -> dict:
        """The stats()/debug block: analytic constants + recent-window
        utilization percentiles + the roofline verdict (median decode
        ratio). Cheap: three window snapshots, no device interaction."""
        decode_ratio = self._roofline_windows["decode"].summary()
        return {
            "peak_flops_per_chip": self._peak_flops,
            "hbm_bw_per_chip": self._hbm_bw,
            "chips": self._n_chips,
            "params": self._costs.params,
            "flops_per_token": self._costs.matmul_flops_per_token,
            "prefill": self._mfu_windows["prefill"].summary(),
            "decode": self._mfu_windows["decode"].summary(),
            "tokens_per_second_per_chip": self._tok_chip_window.summary(),
            "roofline": {
                "prefill": self._roofline_windows["prefill"].summary(),
                "decode": decode_ratio,
                "bound": self._mfu_mod.classify_bound(decode_ratio["p50"]),
            },
        }

    def _ctx_tokens(self, snapshot: list) -> tuple[int, int]:
        """(active requests, summed attended context positions) for one
        chunk step — per-slot context capped at the sliding window, since
        the rolling ring never reads past it."""
        w = self._costs.sliding_window
        active = 0
        ctx = 0
        for r in snapshot:
            if r is None:
                continue
            active += 1
            c = len(r.prompt_tokens) + r.emitted
            ctx += min(c, w) if w else c
        return active, ctx

    def _phase_span(
        self, r: GenRequest, name: str, t0: float, t1: float,
        attrs: dict | None = None,
    ) -> None:
        """Retrospective phase span under the request's llm.request span.
        No-op for untraced requests, so the hot loop pays one None check.
        Timestamps anchor the monotonic interval [t0, t1] to a LIVE wall
        clock read (end = now, start = now - elapsed): a fixed anchor pair
        captured at engine construction would drift out of the parent
        span's live-clock window after any NTP step."""
        if r.span is None:
            return
        end_ns = time.time_ns() - int((time.perf_counter() - t1) * 1e9)
        self.tracer.record_span(
            name, trace_id=r.span.trace_id, parent_id=r.span.span_id,
            start_ns=end_ns - int((t1 - t0) * 1e9), end_ns=end_ns,
            attributes=attrs,
        )

    def _observe_finish(self, r: GenRequest, now: float, fetch_t: float | None = None) -> None:
        """Terminal observability for one request: per-token histogram,
        emit span, llm.request span closure, and the wide-event payload.
        Idempotent (error paths and stale chunk overlap may race the
        regular completion). Queues the wide event for logging OUTSIDE the
        engine lock — the collector calls this under _lock, and a stdout
        write there would serialize emission behind the logger. The whole
        body runs under _lock (re-entrant for the already-locked callers):
        the _observed check-then-set must be atomic against a concurrent
        finisher — close() on a user thread races the scheduler's drain —
        and the _wide_events append must not race _flush_wide_events'
        swap, which would silently drop the line."""
        with self._lock:
            if r._observed:
                return
            r._observed = True
            self._observe_finish_locked(r, now, fetch_t)

    def _observe_finish_locked(self, r: GenRequest, now: float, fetch_t: float | None) -> None:
        r.phase = "done"
        # flush the outstanding-work residue (cancel/shed/eos leave some)
        self._load_tokens -= r._load_acct
        r._load_acct = 0
        if 0 <= r._g_id < len(self._g_refs):
            # release the resident-grammar reference (the table slot
            # becomes evictable once no live request holds it)
            self._g_refs[r._g_id] = max(0, self._g_refs[r._g_id] - 1)
            r._g_id = -1
        if r._aid > 0 and self.lora_slots:
            # release the adapter-pool reference (mirrors the grammar
            # release above; the gid becomes evictable/reclaimable once
            # no in-flight request pins it)
            self._lora_pool.release(r._aid)
            r._aid = 0
        total = None if r.submitted_at is None else now - r.submitted_at
        queue_wait = (
            None if r.admitted_at is None or r.submitted_at is None
            else r.admitted_at - r.submitted_at
        )
        ttft = (
            None if r.first_token_at is None or r.submitted_at is None
            else r.first_token_at - r.submitted_at
        )
        tpot = None
        if r.first_token_at is not None and r.emitted > 1:
            tpot = (now - r.first_token_at) / (r.emitted - 1)
            self._phases["time_per_output_token"].observe(tpot)
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_llm_time_per_output_token_seconds", tpot,
                    exemplar=(
                        {"trace_id": r.span.trace_id}
                        if r.span is not None else None
                    ),
                    **self._role_labels,
                    model=self.label,
                )
        if self.slo is not None and r.finish_reason not in ("cancelled", "disconnect"):
            # SLO verdict: availability counts service failures only — a
            # client that hung up is not our error budget. TTFT/TPOT
            # targets judge in ms; a request that never reached first
            # token but finished "eos"/"length" cannot happen, so None
            # latencies only ride the availability term.
            self.slo.observe(
                tenant=r.adapter or "-",
                priority=r.priority if r.priority == "batch" else "interactive",
                ok=r.finish_reason in ("eos", "length"),
                ttft_ms=None if ttft is None else ttft * 1e3,
                tpot_ms=None if tpot is None else tpot * 1e3,
            )
        # flight record: stamp the terminal outcome (timings, finish
        # reason, emitted token ids) — every terminal path funnels here,
        # so the ring never holds a dangling non-final record for a
        # finished request
        chip = dict(r._chip) if r._chip else {}
        self.flightrec.finalize(
            r,
            queue_wait_ms=None if queue_wait is None else queue_wait * 1e3,
            ttft_ms=None if ttft is None else ttft * 1e3,
            per_token_ms=None if tpot is None else tpot * 1e3,
            total_ms=None if total is None else total * 1e3,
            chip={c: round(v * 1e3, 3) for c, v in chip.items()} or None,
        )
        # perf-anomaly baselines (flightrec): sustained deviation flags
        # app_llm_anomaly and triggers a perf-incident bundle. The step
        # and spec-acceptance signals feed from the scheduler loop.
        if self.anomaly is not None:
            if queue_wait is not None:
                self.anomaly.observe("queue_wait", queue_wait * 1e3)
            if ttft is not None:
                self.anomaly.observe("ttft", ttft * 1e3)
            if tpot is not None:
                self.anomaly.observe("tpot", tpot * 1e3)
        if r.finish_reason == "disconnect":
            # dead-peer cancellation (edge detected a closed connection):
            # the slot is free and the remaining decode was never done —
            # count it so operators see abandoned-stream volume
            self.disconnect_cancels += 1
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_disconnect_cancels_total", model=self.label
                )
        if self.metrics is not None:
            # per-version request accounting (rollouts): which weight set
            # served this request — the canary dashboard's error-rate
            # denominator during a traffic shift
            self.metrics.increment_counter(
                "app_llm_requests_by_version_total",
                model=self.label, version=self.version,
                finish=r.finish_reason or "unknown",
            )
        if r.span is not None:
            if fetch_t is not None:
                # host-side tail: final tokens fetched -> emitted to the
                # consumer queue (detokenization happens at the consumer)
                self._phase_span(r, "llm.emit", fetch_t, now)
            r.span.set_attribute("llm.output_tokens", r.emitted)
            r.span.set_attribute("llm.finish_reason", r.finish_reason)
            if r.prefix_hit:
                r.span.set_attribute("llm.prefix_hit", True)
            if r.finish_reason in ("cancelled", "disconnect", "shed"):
                r.span.set_status("ERROR")
            r.span.end()
        if r.finish_reason in ("error", "poison"):
            self.errored += 1  # bake-window regression signal (rollouts)
        ms = lambda v: None if v is None else round(v * 1e3, 3)  # noqa: E731
        ev = {
            "event": "llm_request",
            "model": self.label,
            "model_version": self.version,
            "id": r.id,
            "trace_id": r.span.trace_id if r.span is not None else "",
            # journey identity: stable across failover/preemption
            # hops (the trace id of the FIRST submit), plus which hop
            # finished the work — `grep journey_id` over the fleet's
            # logs reconstructs the same object the stitcher serves
            "journey_id": r.journey_id or "",
            "hop": r.hop,
            "prompt_tokens": len(r.prompt_tokens),
            "output_tokens": r.emitted,
            "finish_reason": r.finish_reason,
            "queue_wait_ms": ms(queue_wait),
            "ttft_ms": ms(ttft),
            "per_token_ms": ms(tpot),
            "total_ms": ms(total),
            "prefix_hit": r.prefix_hit,
            "capped": r.capped,
            # chip-time attribution (gofr_tpu.goodput): device seconds
            # this request owned, by waste class — the per-request cost
            # line chargeback joins against the tenant usage windows
            "chip_ms": round(sum(chip.values()) * 1e3, 3),
            "chip_breakdown_ms": {
                c: round(v * 1e3, 3) for c, v in chip.items()
            },
        }
        # the FULL stream is retained for incident bundles regardless of
        # sampling or logger presence — a bundle's last-N wide events
        # must not have sampling holes
        self._wide_retained.append(ev)
        if self.logger is not None:
            # 1-in-N sampling (TPU_LLM_WIDE_EVENT_SAMPLE): one JSON line
            # per request is a real cost at the 1k QPS/chip target.
            # Incident lines — anything that didn't finish eos/length,
            # or that survived a death/hop — ALWAYS emit; sampled lines
            # carry the factor so log-derived rates can re-scale.
            self._wide_seq += 1
            forced = (
                r.finish_reason not in ("eos", "length")
                or r.deaths > 0
                or r.hop > 0
            )
            if self._wide_sample <= 1:
                self._wide_events.append(ev)
            elif forced:
                self._wide_events.append({**ev, "sample": 1})
            elif self._wide_seq % self._wide_sample == 0:
                self._wide_events.append({**ev, "sample": self._wide_sample})

    def _flush_wide_events(self) -> None:
        """Emit queued wide-event lines. Called with the lock NOT held."""
        if not self._wide_events:
            return
        with self._lock:
            events, self._wide_events = self._wide_events, []
        for ev in events:
            self.logger.info(ev)

    def _emit_to(self, r: GenRequest, slot: int, toks: list[int], now: float | None = None) -> None:
        """Append a request's next tokens, honoring max_new/eos/cancel.
        Frees the slot only if `r` still owns it (virtual-free admission
        may already have handed the slot to a successor). `now` is the
        fetch-completion time (phase attribution measures device+fetch,
        not the emit loop's position within the batch)."""
        if r.finish_reason is not None:
            return  # already finished; stale chunk overlap
        if self._died:
            # a dying engine must NEVER emit: its recoverable requests are
            # (or are about to be) rescued by the failover hook, and a
            # late emission here would race the continuation's stream on
            # the replacement replica (duplicate tokens). The check runs
            # under _lock — the same lock _die holds while rescuing — so
            # an emission is either fully before the rescue (counted in
            # history) or fully dropped.
            return
        if now is None:
            now = time.perf_counter()
        finish = None
        if r.cancelled:
            toks, finish = [], r.cancel_reason
        take = min(len(toks), r.max_new_tokens - r.emitted)
        toks = toks[:take]
        if r.eos_token >= 0 and r.eos_token in toks:
            toks = toks[: toks.index(r.eos_token) + 1]
            finish = "eos"
        if toks:
            if r.emitted == 0:
                r.first_token_at = now
                r.phase = "decode"
                if r.submitted_at is not None:
                    ttft = now - r.submitted_at
                    self._phases["ttft"].observe(ttft)
                    if self.metrics is not None:
                        # exemplar: the p99 TTFT bucket on /metrics links
                        # the trace id of the request that landed there —
                        # feed it to the journey aggregator for the full
                        # cross-process timeline
                        self.metrics.record_histogram(
                            "app_llm_ttft_seconds", ttft, model=self.label,
                            exemplar=(
                                {"trace_id": r.span.trace_id}
                                if r.span is not None else None
                            ),
                            **self._role_labels,
                        )
                        self.metrics.record_histogram(
                            "app_tpu_queue_wait", ttft, model="llm", op="ttft",
                        )
            r.out.put(toks)
            r.emitted += len(toks)
            r.history.extend(toks)  # failover continuation seed
            if r.grammar is not None:
                # host DFA mirror (drafter filter + continuation re-seed)
                st = r._g_state
                for t in toks:
                    if st < 0:
                        break
                    st = r.grammar.advance(st, t)
                r._g_state = st
            self._load_credit(r, len(toks))
            if self.ledger is not None:
                self.ledger.charge(r.client, len(toks))
        if finish is None and r.emitted >= r.max_new_tokens:
            finish = "length"
        if finish is not None:
            r.finish_reason = finish
            if (
                self.kv.paged and r.session_id
                and finish in ("eos", "length")
                and self.kv.slot_owner(slot) is r
            ):
                # defer the end-of-turn session publish to the scheduler
                # (only it may dispatch against the donated pool); the
                # block sweep keeps this slot's blocks until then
                self._session_pub.append((slot, r))
            self._observe_finish(r, time.perf_counter(), fetch_t=now)
            r.out.put(None)
            if self._slot_req[slot] is r:
                self._slot_req[slot] = None

    def _dispatch(self, needed_steps: int) -> int:
        """Launch one decode chunk chained from the on-device tail and
        return the dispatched chunk length (the scheduler debits it from
        its step budget). All inputs are device-resident — zero h2d
        transfers per chunk. Chunk length adapts to DEMAND, not occupancy:
        the short variant runs only for tail ends (fewer steps needed than
        a short chunk); otherwise the full chunk is dispatched and chained
        eagerly. The r5 engine instead forced short chunks whenever the
        batch was quiet, optimizing speculative TTFT for requests that had
        not arrived at the cost of 3-4x the fetch round trips for the
        requests actually in flight (BENCH_r05: 507 ms completion p50 at
        25 QPS against a ~100 ms TTFT floor). Demand-sized chunks finish
        an 8-token completion in ~2 RTTs (prefill + one covering chunk);
        a fresh arrival waits at most one chunk, and the collector's
        prefill-priority jump still fetches its first token ahead of
        queued chunk fetches. The saturated path is unchanged (full chunks
        either way)."""
        self._ship_aids()
        with self._work_cv:
            # partial-prefill occupants are resident but NOT decoding:
            # the chunk's tokens for their slots are garbage (device
            # active mask is off), so they are snapshot-excluded exactly
            # like free slots
            snapshot = [
                r if (r is not None and r.prefill_done) else None
                for r in self._slot_req
            ]
            active_n = sum(r is not None for r in snapshot)
            k = (
                self._chunk_short
                if needed_steps <= self._chunk_short
                else self.decode_chunk
            )
            self._fault("device_step")
            t0 = time.perf_counter()
            # constrained family when ANY resident request carries a
            # grammar: per-slot gids mask only their own lanes, so
            # unconstrained neighbors stay token-identical, and the
            # device DFA state chain stays coherent across dispatches
            use_g = self.constrained and self._grammar_live()
            if use_g:
                self._ensure_c_ops()
                gids = self._jnp.asarray(self._gids_np())
            if self.kv.paged:
                # allocate blocks ahead of the chunk's cursor advance and
                # build the host liveness mask. Two exclusions: stale
                # lanes (their tables may name reassigned blocks) and
                # SATISFIED lanes — a request whose in-flight coverage
                # already reaches max_new must stop advancing, or chunks
                # driven by OTHER slots' demand would walk its device
                # length past the materialized watermark and scatter
                # through stale table entries (cross-slot corruption;
                # the contiguous path could afford the clamped garbage)
                steps = self._inflight_steps()
                live = np.zeros((self.slots,), bool)
                for i, r in enumerate(snapshot):
                    if r is None:
                        continue
                    if r.emitted + steps.get(i, 0) >= r.max_new_tokens:
                        continue
                    live[i] = True
                    self._kv_hi[i] = min(
                        self._kv_hi[i] + k, r._kv_limit or self.kv.capacity
                    )
                    self.kv.ensure(i, self._kv_hi[i])
                td = self._tables_device()
                with self._hb_dispatch.beat("dispatch:chunk"):
                    if use_g:
                        (
                            toks, last, self.cache, self._kv_scales,
                            self._gstate, self._rng,
                        ) = self._chunk_ops_c[k](
                            self.params, self._tail, self.cache,
                            self._kv_scales, td, self._jnp.asarray(live),
                            self._active, self._temps, self._gstate,
                            gids, self._rng, self._gr_dev,
                        )
                    else:
                        toks, last, self.cache, self._kv_scales, self._rng = (
                            self._chunk_ops[k](
                                self.params, self._tail, self.cache,
                                self._kv_scales, td, self._jnp.asarray(live),
                                self._active, self._temps, self._rng,
                            )
                        )
            else:
                with self._hb_dispatch.beat("dispatch:chunk"):
                    if use_g:
                        toks, last, self.cache, self._gstate, self._rng = (
                            self._chunk_ops_c[k](
                                self.params, self._tail, self.cache,
                                self._active, self._temps, self._gstate,
                                gids, self._rng, self._gr_dev,
                            )
                        )
                    else:
                        toks, last, self.cache, self._rng = self._chunk_ops[k](
                            self.params, self._tail, self.cache,
                            self._active, self._temps, self._rng,
                        )
            self._tail = last
            self._start_fetch(toks)
            self._inflight.append(("chunk", toks, snapshot, k, t0))
            self._stat_chunks += 1
            self._stat_chunk_steps += k
            self._stat_active_sum += active_n
            self._work_cv.notify()
            return k

    def _chunk_shape_for(self, n: int) -> int:
        """Compile shape for a chunk covering n pending tokens: the
        smallest available shape that fits, else the largest (the prompt
        then takes multiple chunks). The configured prefill buckets
        survive exactly here — as chunk shapes — so short prompts keep
        their tight compile shapes instead of padding to prefill_chunk."""
        for s in self.chunk_shapes:
            if n <= s:
                return s
        return self.chunk_shapes[-1]

    def _dispatch_step(self) -> bool:
        """Pack one unified device step: one decode chunk for the active
        slots fused with up to admit_cap pending prefill chunks. The
        decode tokens are charged against step_token_budget first and
        prefill coalescing fills what remains, floored at one chunk — the
        budget bounds the step, it is never a stall gate. Decode rides
        EVERY step unconditionally: it is exactly the work whose
        starvation the budget exists to prevent, its per-step cost is one
        bounded chunk, and rows whose prompt completes this step decode
        immediately in the same program (an all-inactive decode part is
        masked work that only occurs during cold prefill ramp). Returns
        False when every queued prefill row turned out stale
        (reassigned/cancelled)."""
        jnp = self._jnp
        self._ship_aids()
        self._fault("device_step")  # before any cursor mutation
        with self._work_cv:
            # purge stale prefill rows (cancelled, or slot reassigned)
            rows: list[tuple[GenRequest, int]] = []  # (request, n_new)
            K = self.decode_chunk
            active_n = sum(
                1 for r in self._slot_req if r is not None and r.prefill_done
            )
            shape = 0
            budget_left = 0
            keep: deque[GenRequest] = deque()
            while self._prefilling:
                r = self._prefilling.popleft()
                if (
                    r.slot is None
                    or self._slot_req[r.slot] is not r
                    or r.prefill_done
                ):
                    continue  # slot lost (recovery) or already finished
                if r.cancelled:
                    if r.finish_reason is None:
                        r.finish_reason = r.cancel_reason
                        self._observe_finish(r, time.perf_counter())
                        r.out.put(None)
                    self._slot_req[r.slot] = None
                    continue
                rem = len(r.prompt_tokens) - r.prefill_pos
                if not rows:
                    # first row fixes the step's compile shape and the
                    # prefill allowance: total budget minus the decode
                    # tokens riding this step, floored at one chunk
                    shape = self._chunk_shape_for(rem)
                    budget_left = max(
                        min(rem, shape), self.step_token_budget - K * active_n
                    )
                n = min(shape, rem)
                if len(rows) == self.admit_cap or n > budget_left:
                    keep.append(r)  # head-of-line stays FIFO for next step
                    break
                rows.append((r, n))
                budget_left -= n
                if r.prefill_pos + n < len(r.prompt_tokens):
                    keep.append(r)  # more chunks to come
            keep.extend(self._prefilling)
            self._prefilling = keep
            if not rows:
                return False
            now = time.perf_counter()
            nb = self._wave_width(len(rows))
            pack = np.zeros((nb, shape + 3), np.int32)
            # meta rows 2/3 (grammar id, start DFA state) ride only the
            # constrained program family; the plain op takes meta[:2]
            meta = np.zeros((4, nb), np.int32)
            meta[0, :] = self.slots  # pad lanes: inert (scatters dropped)
            meta[2, :] = -1  # pad/unconstrained lanes: no grammar
            finishes: list[tuple[int, int, GenRequest]] = []
            prefill_tokens = 0
            spans: list[tuple[int, int]] = []  # (cursor, n) for MFU
            for j, (r, n) in enumerate(rows):
                pos = r.prefill_pos
                pack[j, :n] = r.prompt_tokens[pos : pos + n]
                pack[j, shape] = pos
                pack[j, shape + 1] = n
                pack[j, shape + 2] = np.float32(r.temperature).view(np.int32)
                meta[0, j] = r.slot
                done = pos + n >= len(r.prompt_tokens)
                meta[1, j] = 1 if done else 0
                if r.grammar is not None and r._g_id >= 0 and r._g_state >= 0:
                    # first-token mask + device-state seed for the row's
                    # slot: fresh requests start at the DFA start state,
                    # continuations at the host mirror's state (a dead
                    # mirror — cannot happen while masking holds — keeps
                    # the lane unconstrained rather than wrong-state)
                    meta[2, j] = r._g_id
                    meta[3, j] = r._g_state
                if r._prefill_t0 is None:
                    r._prefill_t0 = now
                r.prefill_pos = pos + n
                # rows actually written: the append scatter drops indices
                # at i >= n, so padding past the valid count never lands —
                # retaining pos + shape would store garbage rows in the
                # prefix cache and bill them against its byte budget
                r._rows_hi = max(r._rows_hi, pos + n)
                if self.kv.paged:
                    # blocks for the appended rows (+ the fused decode
                    # chunk when this row activates)
                    hi = pos + n + (K if done else 0)
                    self._kv_hi[r.slot] = min(
                        max(self._kv_hi[r.slot], hi),
                        r._kv_limit or self.kv.capacity,
                    )
                    self.kv.ensure(r.slot, self._kv_hi[r.slot])
                self._load_credit(r, n)
                prefill_tokens += n
                spans.append((pos, n))
                if done:
                    r.prefill_done = True
                    finishes.append((j, r.slot, r))
            use_g = self.constrained and (
                self._grammar_live()
                or any(m >= 0 for m in meta[2, : len(rows)])
            )
            if use_g:
                self._ensure_c_ops()
                op = self._step_ops_c[shape]
                gids = self._jnp.asarray(self._gids_np())
            else:
                op = self._step_ops[shape]
            t0 = time.perf_counter()
            if self.kv.paged:
                steps_cov = self._inflight_steps()
                live = np.zeros((self.slots,), bool)
                for i, r in enumerate(self._slot_req):
                    if r is None or not r.prefill_done:
                        continue
                    if (
                        r.emitted + steps_cov.get(i, 0) >= r.max_new_tokens
                        and not any(s == i for _j, s, _r in finishes)
                    ):
                        # satisfied lane: must not advance past its
                        # materialized blocks (see _dispatch)
                        continue
                    live[i] = True
                    if not any(s == i for _j, s, _r in finishes):
                        # already-decoding slots advance K this step
                        self._kv_hi[i] = min(
                            self._kv_hi[i] + K,
                            r._kv_limit or self.kv.capacity,
                        )
                        self.kv.ensure(i, self._kv_hi[i])
                td = self._tables_device()
                with self._hb_dispatch.beat("dispatch:step"):
                    if use_g:
                        (first_dev, logits_dev, toks_dev, last, cache,
                         self._kv_scales, active, temps, self._gstate,
                         rng) = op(
                            self.params, self.cache, self._kv_scales, td,
                            jnp.asarray(live), self._tail, self._active,
                            self._temps, self._gstate, jnp.asarray(pack),
                            jnp.asarray(meta), gids, self._rng,
                            self._gr_dev,
                        )
                    else:
                        (first_dev, logits_dev, toks_dev, last, cache,
                         self._kv_scales, active, temps, rng) = op(
                            self.params, self.cache, self._kv_scales, td,
                            jnp.asarray(live), self._tail, self._active,
                            self._temps, jnp.asarray(pack),
                            jnp.asarray(meta[:2]), self._rng,
                        )
            else:
                with self._hb_dispatch.beat("dispatch:step"):
                    if use_g:
                        (first_dev, logits_dev, toks_dev, last, cache,
                         active, temps, self._gstate, rng) = op(
                            self.params, self.cache, self._tail,
                            self._active, self._temps, self._gstate,
                            jnp.asarray(pack), jnp.asarray(meta), gids,
                            self._rng, self._gr_dev,
                        )
                    else:
                        (first_dev, logits_dev, toks_dev, last, cache,
                         active, temps, rng) = op(
                            self.params, self.cache, self._tail,
                            self._active, self._temps, jnp.asarray(pack),
                            jnp.asarray(meta[:2]), self._rng,
                        )
            self._tail = last
            self.cache, self._active, self._temps, self._rng = (
                cache, active, temps, rng,
            )
            if finishes:
                self._start_fetch(first_dev)
            self._start_fetch(toks_dev)
            # retain finished prompts for prefix reuse: contiguous rows
            # sliced from the slot cache AFTER the append (device-ordered
            # before any later mutation) / paged blocks shared in place
            if self.kv.paged and self.kv.share:
                for j, slot, r in finishes:
                    self._kv_publish(
                        slot, r,
                        None if logits_dev is None else logits_dev[j : j + 1],
                    )
            elif self.kv.prefix is not None and logits_dev is not None:
                for j, slot, r in finishes:
                    if r._aid != 0:
                        # adapted rows hold tenant-delta K/V — never
                        # shareable through the base prefix cache
                        continue
                    keep_rows = (
                        self.kv.capacity if self.kv.rolling
                        else min(r._rows_hi, self.kv.capacity)
                    )
                    self.kv.prefix.put(
                        self.kv.prefix.key_for(r.prompt_tokens),
                        cache.k[:, slot : slot + 1, :keep_rows],
                        cache.v[:, slot : slot + 1, :keep_rows],
                        len(r.prompt_tokens), logits_dev[j : j + 1],
                    )
            # snapshot AFTER the rows loop: rows finishing this step have
            # prefill_done set and their decode runs in this program
            snapshot = [
                r if (r is not None and r.prefill_done) else None
                for r in self._slot_req
            ]
            decode_n = active_n + len(finishes)
            step_tokens = prefill_tokens + K * decode_n
            info = {
                "t0": t0, "shape": shape, "nb": nb,
                "prefill_tokens": prefill_tokens, "spans": spans,
                "active": active_n,
                # row requests aligned with spans — the goodput ledger
                # attributes each prefill span to its owner at the fetch
                "rows": [r for r, _n in rows],
            }
            self._inflight.append(
                ("step", first_dev, finishes, toks_dev, snapshot, K, info)
            )
            self._stat_steps += 1
            self._stat_step_tokens += step_tokens
            if decode_n:
                self._stat_chunks += 1
                self._stat_chunk_steps += K
                self._stat_active_sum += decode_n
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_llm_step_tokens", float(step_tokens), model=self.label
                )
                self.metrics.set_gauge(
                    "app_llm_step_budget_utilization",
                    step_tokens / self.step_token_budget, model=self.label,
                )
            self._work_cv.notify()
            return True

    def _spec_drafts(self, r: GenRequest) -> tuple[list[int], list[int]]:
        """(draft, predicted emitted span) for one decoding slot: draft
        length adapts to the request's acceptance EMA
        (gofr_tpu.spec.draft_len — backed-off requests run plain decode
        with a periodic 1-token probe), capped at the tokens the request
        can still emit; proposals come from the n-gram drafter over the
        OPTIMISTIC stream — prompt + emitted history + the predicted
        spans of verifies still in flight — which is what lets verify
        steps pipeline to `lookahead` depth instead of exposing a full
        dispatch->fetch round trip per step. The predicted span
        (draft + one predicted bonus token) is what the verify will emit
        if everything is accepted; a misprediction only mis-aims LATER
        drafts (they get rejected), never the emitted stream. Call with
        the lock held."""
        from .spec import draft_len

        emitted_opt = r.emitted + len(r._spec_pending)
        kmax = min(self.spec_draft, r.max_new_tokens - emitted_opt - 1)
        k = draft_len(r._spec_ema, kmax, r._spec_plain)
        if k <= 0:
            r._spec_plain += 1
            last = (
                r._spec_pending[-1] if r._spec_pending
                else r.history[-1] if r.history
                else r.prompt_tokens[-1] if r.prompt_tokens else 0
            )
            return [], [last]
        # ONE drafter call for k+1 tokens: the first k are the draft,
        # the overhang predicts the bonus token for the optimistic
        # pending stream — a second full-stream scan just to aim one
        # token would double the per-slot host cost on the scheduler
        # thread (the drafter's byte-scan design exists to keep this
        # cheap)
        stream = r.prompt_tokens + r.history + r._spec_pending
        d_full = self.drafter.draft(stream, k + 1)
        d = d_full[:k]
        if r.grammar is not None:
            # grammar-aware drafting (docs/advanced-guide/
            # structured-decoding.md), two moves on the host DFA mirror
            # advanced over the optimistic pending spans:
            # 1. FILTER — an inadmissible proposal is GUARANTEED
            #    rejection (the verify's masked sample cannot equal it),
            #    so cut the draft at the first token the DFA refuses;
            # 2. FAST-FORWARD — wherever the grammar admits EXACTLY ONE
            #    token (fixed property names, structural punctuation,
            #    literal tails), that token is a guaranteed-accept draft
            #    position: extend the draft through forced runs even
            #    when the n-gram drafter proposed nothing. This is what
            #    lifts constrained acceptance above the unconstrained
            #    baseline on schema-shaped output.
            st = r._g_state
            for t in r._spec_pending:
                if st < 0:
                    break
                st = r.grammar.advance(st, t)
            g_bonus: list[int] = []
            if st < 0:
                d = []
            else:
                d = r.grammar.filter_draft(st, d)
                s = st
                for t in d:
                    s = r.grammar.advance(s, t)
                while len(d) < k and s >= 0:
                    forced = np.flatnonzero(r.grammar.allowed(s))
                    if len(forced) != 1:
                        break
                    t = int(forced[0])
                    d.append(t)
                    s = r.grammar.advance(s, t)
                if d and s >= 0:
                    # grammar-forced BONUS aim: when the state after the
                    # draft admits exactly one token, the verify's bonus
                    # sample IS that token — a certain prediction keeps
                    # the optimistic pending stream (hence the next
                    # pipelined verify's drafts) on target
                    forced = np.flatnonzero(r.grammar.allowed(s))
                    if len(forced) == 1:
                        g_bonus = [int(forced[0])]
            if not d:
                r._spec_plain += 1
                return [], [stream[-1] if stream else 0]
            bonus = g_bonus or (
                (d_full[k : k + 1] if len(d) == k else d[-1:]) or d[-1:]
            )
            return d, d + bonus
        if not d:
            r._spec_plain += 1
            return [], [stream[-1] if stream else 0]
        bonus = d_full[k : k + 1] or d[-1:]
        return d, d + bonus

    def _dispatch_verify(self) -> bool:
        """Dispatch one fused speculative verify step (gofr_tpu.spec):
        every decoding slot whose in-flight coverage is verify-only gets
        its draft packed into one full-batch llm.step_v program; lanes
        whose drafter proposed nothing ride as draft-0 plain decode, so
        speculation never splits the batch. Verifies PIPELINE to
        `lookahead` depth: the program chains tail/cursor from device
        state, so a verify dispatched before its predecessor's fetch is
        still an exact continuation — only its drafts (aimed by the
        optimistic pending stream) can go stale, costing acceptance,
        never correctness. Selected lanes charge W = draft+1 tokens each
        against the step token budget (floored at one lane — the budget
        bounds the step, it is not a stall gate). Returns False when no
        slot was eligible OR nothing was drafted anywhere — the caller
        then runs the plain chunk pipeline, which is the adaptive
        backoff's no-regression guarantee at engine scope."""
        jnp = self._jnp
        self._ship_aids()
        self._fault("device_step")
        with self._work_cv:
            steps = self._inflight_steps()
            # verify-only coverage per slot: a slot whose ENTIRE in-flight
            # coverage is verify entries may pipeline another verify (its
            # optimistic pending stream tracks those); any chunk/step
            # coverage means un-predicted tokens are coming — wait for
            # the fetch
            ver_cover: dict[int, int] = {}
            entries = list(self._inflight)
            if self._processing is not None:
                entries.append(self._processing)
            for e in entries:
                if e[0] == "verify":
                    for slot, r in e[3]:
                        if r is self._slot_req[slot]:
                            ver_cover[slot] = ver_cover.get(slot, 0) + 1
            Kd = self.spec_draft
            W = Kd + 1
            budget = self.step_token_budget or self.slots * W
            pack = np.zeros((self.slots, Kd + 2), np.int32)
            sel: list[tuple[int, GenRequest]] = []
            proposed = 0
            cursors: dict[int, int] = {}
            n_draft: dict[int, int] = {}
            pred: dict[int, list[int]] = {}
            # Rotated scan: when the step budget cuts the selection short,
            # the next dispatch starts where this one stopped — without
            # the rotation, slots past floor(budget/W) would NEVER be
            # selected (and the chunk pipeline is blocked while verifies
            # fly), starving their requests under sustained admissions
            # into the low slots.
            start = self._spec_rr % self.slots
            cut: int | None = None
            for slot in (
                list(range(start, self.slots)) + list(range(0, start))
            ):
                r = self._slot_req[slot]
                if (
                    r is None
                    or not r.prefill_done
                    or r.cancelled
                    or r.finish_reason is not None
                    or steps.get(slot, 0) != ver_cover.get(slot, 0)
                    or r.emitted + len(r._spec_pending) >= r.max_new_tokens
                ):
                    continue
                if sel and (len(sel) + 1) * W > budget:
                    cut = slot
                    break
                d, p = self._spec_drafts(r)
                pack[slot, : len(d)] = d
                pack[slot, Kd] = len(d)
                pack[slot, Kd + 1] = 1
                sel.append((slot, r))
                proposed += len(d)
                n_draft[slot] = len(d)
                pred[slot] = p
                cursors[slot] = (
                    len(r.prompt_tokens) + r.emitted + len(r._spec_pending)
                )
            if not sel or not proposed:
                # nothing drafted anywhere: plain decode through the
                # chunk pipeline is strictly better (chained dispatches
                # hide the fetch RTT a 1-wide verify would expose) — the
                # scheduler falls back to _dispatch for this pass
                return False
            if cut is not None:
                self._spec_rr = cut  # resume the budget-cut scan here
            for slot, r in sel:
                r._spec_pending = r._spec_pending + pred[slot]
                r._spec_inflight += 1
                if not n_draft[slot]:
                    self.spec_plain += 1
            # constrained split: acceptance on grammar-masked text is the
            # structured-decoding bench signal (drafts were pre-filtered
            # by the DFA in _spec_drafts, so acceptance should not drop)
            gset = {slot for slot, r in sel if r.grammar is not None}
            proposed_c = sum(n_draft[s] for s in gset)
            use_g = self.constrained and self._grammar_live()
            if use_g:
                self._ensure_c_ops()
                gids_dev = jnp.asarray(self._gids_np())
            t0 = time.perf_counter()
            if self.kv.paged:
                # blocks for the verify's transient rows: [length,
                # length + W) per selected lane — the rollback leaves
                # rejected rows in PRIVATE blocks above the cursor,
                # rewritten by the next append (the contiguous path's
                # stale-row contract, at block granularity)
                for slot, r in sel:
                    self._kv_hi[slot] = min(
                        self._kv_hi[slot] + W,
                        r._kv_limit or self.kv.capacity,
                    )
                    self.kv.ensure(slot, self._kv_hi[slot])
                td = self._tables_device()
                with self._hb_dispatch.beat("dispatch:verify"):
                    if use_g:
                        (ys, acc, cache, self._kv_scales, tail,
                         self._gstate, self._rng) = self._verify_op_c(
                            self.params, self.cache, self._kv_scales, td,
                            self._tail, self._temps, self._gstate,
                            jnp.asarray(pack), gids_dev, self._rng,
                            self._gr_dev,
                        )
                    else:
                        ys, acc, cache, self._kv_scales, tail, self._rng = (
                            self._verify_op(
                                self.params, self.cache, self._kv_scales,
                                td, self._tail, self._temps,
                                jnp.asarray(pack), self._rng,
                            )
                        )
            else:
                with self._hb_dispatch.beat("dispatch:verify"):
                    if use_g:
                        ys, acc, cache, tail, self._gstate, self._rng = (
                            self._verify_op_c(
                                self.params, self.cache, self._tail,
                                self._temps, self._gstate,
                                jnp.asarray(pack), gids_dev, self._rng,
                                self._gr_dev,
                            )
                        )
                    else:
                        ys, acc, cache, tail, self._rng = self._verify_op(
                            self.params, self.cache, self._tail,
                            self._temps, jnp.asarray(pack), self._rng,
                        )
            self.cache, self._tail = cache, tail
            self._start_fetch(ys)
            self._start_fetch(acc)
            step_tokens = W * len(sel)
            info = {
                "t0": t0, "W": W, "proposed": proposed,
                "n_draft": n_draft, "cursors": cursors, "pred": pred,
                "gset": gset,
            }
            self._inflight.append(("verify", ys, acc, sel, info))
            self.spec_steps += 1
            self.spec_proposed += proposed
            self.spec_proposed_c += proposed_c
            self._stat_steps += 1
            self._stat_step_tokens += step_tokens
            if self.metrics is not None:
                if proposed - proposed_c:
                    self.metrics.increment_counter(
                        "app_llm_spec_proposed_total",
                        by=float(proposed - proposed_c),
                        model=self.label, constrained="0",
                    )
                if proposed_c:
                    self.metrics.increment_counter(
                        "app_llm_spec_proposed_total", by=float(proposed_c),
                        model=self.label, constrained="1",
                    )
                self.metrics.record_histogram(
                    "app_llm_step_tokens", float(step_tokens),
                    model=self.label,
                )
                if self.step_token_budget:
                    self.metrics.set_gauge(
                        "app_llm_step_budget_utilization",
                        step_tokens / self.step_token_budget,
                        model=self.label,
                    )
            self._work_cv.notify()
            return True

    def _process_entry(self, entry: tuple) -> None:
        """Fetch one device result (outside the lock — the blocking RTT
        must not stall the scheduler) and emit tokens (under the lock)."""
        if entry[0] == "verify":
            self._process_verify_entry(entry)
            return
        if entry[0] == "step":
            self._process_step_entry(entry)
            return
        if entry[0] == "prefill":
            _, first_dev, taken, info = entry
            first = np.asarray(first_dev)
            # numerical watchdog: scan BEFORE any emission, outside the
            # lock (_die must not run under our own lock — the failover
            # hook submits into other engines)
            first, tripped = self._numeric_check_fetch(
                first,
                [j for j, (_s, r) in enumerate(taken) if r is not None],
                "prefill first token",
            )
            if tripped:
                return
            now = time.perf_counter()
            if info["bucket"] is not None:  # miss wave: a device prefill ran
                # (prefix-hit waves dispatch no prefill — no MFU to claim)
                seq_lens = [
                    len(r.prompt_tokens) for _, r in taken if r is not None
                ]
                self._observe_tput(sum(seq_lens), now - info["t0"])
                self._observe_mfu(
                    "prefill",
                    tokens=sum(seq_lens),
                    flops=self._mfu_mod.prefill_flops(self._costs, seq_lens),
                    bytes_moved=(
                        self._costs.params_bytes
                        + sum(seq_lens) * self._costs.kv_bytes_per_ctx_token
                    ),
                    dt=now - info["t0"],
                )
            if self.goodput is not None:
                from .goodput import prefill_classes

                # miss wave: the device ran [nb, bucket] prompt rows —
                # live lanes own their prompt length (replay-split for
                # continuations), everything else in the rectangle is
                # padding (scrubbed lanes included). A prefix-hit wave
                # dispatched no prefill; its cost is ~the one seeded
                # first-token sample per lane.
                lanes: list = []
                plen_sum = 0
                for _s, r in taken:
                    if r is None:
                        continue
                    if info["bucket"] is not None:
                        plen = len(r.prompt_tokens)
                        lanes.append(
                            (r, prefill_classes(r._replay_pos, 0, plen))
                        )
                        plen_sum += plen
                    else:
                        lanes.append((r, {"useful": 1}))
                if info["bucket"] is not None:
                    pad = (
                        info["bucket"] * max(info["nb"], len(taken))
                        - plen_sum
                    )
                    if pad > 0:
                        lanes.append((None, {"padding": pad}))
                self.goodput.observe("prefill", info["t0"], now, lanes)
            with self._lock:
                for j, (slot, r) in enumerate(taken):
                    if r is None:  # scrubbed by preemption: tokens dropped
                        continue
                    if r.span is not None and r.finish_reason is None:
                        self._phase_span(
                            r, "llm.prefill", info["t0"], now,
                            attrs={
                                "llm.wave": info["nb"] or len(taken),
                                "llm.bucket": info["bucket"] or 0,
                                "llm.prefix_hit": r.prefix_hit,
                            },
                        )
                    self._emit_to(r, slot, [int(first[j])], now)
                self._processing = None  # same acquisition as the emits —
                # a separate clear would let the scheduler double-count
                # this entry in _inflight_steps after emitted already grew
            if self.logger is not None:
                self._flush_wide_events()
            return
        _, toks_dev, snapshot, k, t_dispatch = entry
        t0 = time.perf_counter()
        toks = np.asarray(toks_dev)  # [K, S] — blocks; device runs next chunk
        toks, tripped = self._numeric_check_fetch(
            toks, [s for s, r in enumerate(snapshot) if r is not None],
            "decode chunk",
        )
        if tripped:
            return
        now = time.perf_counter()
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_stats", now - t0,
                model="llm", op="decode_chunk",
            )
        # dispatch->fetch cost per decode step, attributed once per chunk
        # (wave = active slots at dispatch, bucketed to a power of two so
        # the label set stays bounded at log2(slots) values)
        active_n, ctx_sum = self._ctx_tokens(snapshot)
        self._observe_tput(k * active_n, now - t_dispatch)
        step_s = (now - t_dispatch) / k
        self._phases["decode_step"].observe(step_s)
        if active_n:
            # each of the k steps decodes one token per active slot and
            # re-streams the weights + the live KV prefix
            self._observe_mfu(
                "decode",
                tokens=k * active_n,
                flops=self._mfu_mod.decode_flops(
                    self._costs, k * active_n, k * ctx_sum
                ),
                bytes_moved=k * (
                    self._costs.params_bytes
                    + ctx_sum * self._costs.kv_bytes_per_ctx_token
                ),
                dt=now - t_dispatch,
            )
        if self.metrics is not None:
            wave = 1 << max(0, active_n - 1).bit_length() if active_n else 0
            self.metrics.record_histogram(
                "app_llm_decode_step_seconds", step_s,
                model=self.label, chunk=str(k), wave=str(wave), fused="0",
                **self._role_labels,
            )
        if self.goodput is not None:
            # dense decode pass: every slot lane ran k serial steps —
            # live lanes decoded useful tokens (capped at the request's
            # remaining budget: positions computed past max_new are
            # truncated at emit, i.e. slack, not demand), empty lanes
            # are padding
            lanes = []
            for r in snapshot:
                if r is None:
                    continue
                use = min(k, max(0, r.max_new_tokens - r.emitted))
                cl = {"useful": use}
                if k - use > 0:
                    cl["padding"] = k - use
                lanes.append((r, cl))
            dead = k * (len(snapshot) - active_n)
            if dead > 0:
                lanes.append((None, {"padding": dead}))
            self.goodput.observe("chunk", t_dispatch, now, lanes)
        cols = toks.T  # [S, K]
        with self._lock:
            for slot, r in enumerate(snapshot):
                if r is not None:
                    if r.span is not None and r.finish_reason is None:
                        self._phase_span(
                            r, "llm.decode", t_dispatch, now,
                            attrs={"llm.chunk": k, "llm.active": active_n,
                                   "llm.slot": slot},
                        )
                    self._emit_to(r, slot, cols[slot].tolist(), now)
            self._processing = None
        if self.logger is not None:
            self._flush_wide_events()

    def _process_step_entry(self, entry: tuple) -> None:
        """Fetch and emit one unified step: first tokens for rows whose
        prompt completed this step (their llm.prefill span closes here),
        then the piggybacked decode chunk's columns. MFU accounting is
        per-step — one prefill observation over the chunk spans and one
        decode observation over the chunk, both against the step's
        dispatch->fetch wall (they share the device window; read the
        window percentiles, never sum them)."""
        _, first_dev, finishes, toks_dev, snapshot, k, info = entry
        t0 = time.perf_counter()
        first = np.asarray(first_dev) if finishes else None
        toks = np.asarray(toks_dev)
        # numerical watchdog: both fetched arrays, before any emission
        if first is not None:
            first, tripped = self._numeric_check_fetch(
                first, [j for j, _s, _r in finishes], "step first token",
            )
            if tripped:
                return
        toks, tripped = self._numeric_check_fetch(
            toks, [s for s, r in enumerate(snapshot) if r is not None],
            "step decode",
        )
        if tripped:
            return
        decoded = any(r is not None for r in snapshot)
        now = time.perf_counter()
        step_s = now - info["t0"]
        self._observe_tput(
            info["prefill_tokens"]
            + k * sum(1 for r in snapshot if r is not None),
            step_s,
        )
        self._phases["step"].observe(step_s)
        if self.anomaly is not None:
            self.anomaly.observe("step", step_s * 1e3)
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_llm_step_seconds", step_s, model=self.label,
                **self._role_labels,
            )
            if decoded:
                self.metrics.record_histogram(
                    "app_tpu_stats", now - t0, model="llm", op="decode_chunk",
                )
        if info["prefill_tokens"]:
            ctx_read = sum(
                min(pos, self._costs.sliding_window) if self._costs.sliding_window
                else pos
                for pos, _n in info["spans"]
            )
            self._observe_mfu(
                "prefill",
                tokens=info["prefill_tokens"],
                flops=self._mfu_mod.chunk_prefill_flops(
                    self._costs, info["spans"]
                ),
                bytes_moved=(
                    self._costs.params_bytes
                    + (info["prefill_tokens"] + ctx_read)
                    * self._costs.kv_bytes_per_ctx_token
                ),
                dt=step_s,
            )
        if decoded:
            active_n, ctx_sum = self._ctx_tokens(snapshot)
            # per-token cadence requests actually experience: a fused
            # step's wall includes its prefill-append compute (a short
            # request may complete entirely inside its own step, so
            # skipping fused steps would leave the series empty for it)
            self._phases["decode_step"].observe(step_s / k)
            if active_n:
                self._observe_mfu(
                    "decode",
                    tokens=k * active_n,
                    flops=self._mfu_mod.decode_flops(
                        self._costs, k * active_n, k * ctx_sum
                    ),
                    bytes_moved=k * (
                        self._costs.params_bytes
                        + ctx_sum * self._costs.kv_bytes_per_ctx_token
                    ),
                    dt=step_s,
                )
            if self.metrics is not None:
                # fused="1" marks walls that include prefill-append compute
                # — filter to fused="0" for decode cost comparable 1:1 with
                # the wave scheduler's pure-decode dispatches
                wave = 1 << max(0, active_n - 1).bit_length() if active_n else 0
                self.metrics.record_histogram(
                    "app_llm_decode_step_seconds", step_s / k,
                    model=self.label, chunk=str(k), wave=str(wave),
                    fused="1" if info["prefill_tokens"] else "0",
                    **self._role_labels,
                )
        if self.goodput is not None:
            from .goodput import prefill_classes

            # fused step: each packed prefill span belongs to its row's
            # request (replay-split for continuations); the piggybacked
            # decode ran k steps over ALL slot lanes. Padding = unpacked
            # prefill rectangle + empty decode lanes.
            lanes = []
            for r, (pos, n) in zip(info.get("rows", ()), info["spans"]):
                lanes.append((r, prefill_classes(r._replay_pos, pos, n)))
            decode_n = 0
            for r in snapshot:
                if r is not None:
                    decode_n += 1
                    use = min(k, max(0, r.max_new_tokens - r.emitted))
                    cl = {"useful": use}
                    if k - use > 0:
                        cl["padding"] = k - use
                    lanes.append((r, cl))
            pad = (
                info["shape"] * info["nb"] - info["prefill_tokens"]
                + k * (len(snapshot) - decode_n)
            )
            if pad > 0:
                lanes.append((None, {"padding": pad}))
            self.goodput.observe("step", info["t0"], now, lanes)
        with self._lock:
            for j, slot, r in finishes:
                if r.span is not None and r.finish_reason is None:
                    self._phase_span(
                        r, "llm.prefill", r._prefill_t0 or info["t0"], now,
                        attrs={
                            "llm.wave": info["nb"],
                            "llm.bucket": info["shape"],
                            "llm.prefix_hit": r.prefix_hit,
                        },
                    )
                self._emit_to(r, slot, [int(first[j])], now)
            if decoded:
                cols = toks.T  # [S, K]
                for slot, r in enumerate(snapshot):
                    if r is not None:
                        if r.span is not None and r.finish_reason is None:
                            self._phase_span(
                                r, "llm.decode", info["t0"], now,
                                attrs={"llm.chunk": k, "llm.active":
                                       info["active"], "llm.slot": slot},
                            )
                        self._emit_to(r, slot, cols[slot].tolist(), now)
            self._processing = None  # same acquisition as the emits
        if self.logger is not None:
            self._flush_wide_events()

    def _process_verify_entry(self, entry: tuple) -> None:
        """Fetch and emit one speculative verify step: per selected slot,
        the accepted draft tokens plus the bonus token (``ys[:acc+1]``)
        feed the existing emit path as ONE multi-token push — max_new /
        eos truncation, load_tokens credit, and the fairness ledger all
        see exactly the emitted count. Acceptance telemetry updates the
        per-request EMA that sizes the next draft, and MFU bills only
        the accepted tokens (verified-but-rejected positions are
        non-useful work — profiling.mfu.spec_verify_flops)."""
        _, ys_dev, acc_dev, sel, info = entry
        ys = np.asarray(ys_dev)  # [S, W]
        acc = np.asarray(acc_dev)  # [S]
        # numerical watchdog: live lanes scanned BEFORE any emission
        # (lanes are rows here; the helper scans last-axis columns)
        ys_t, tripped = self._numeric_check_fetch(
            ys.T, [slot for slot, _r in sel], "spec verify",
        )
        if tripped:
            return
        ys = ys_t.T
        now = time.perf_counter()
        dt = now - info["t0"]
        w = self._costs.sliding_window
        emitted_total = 0
        accepted_total = 0
        spans: list[tuple[int, int]] = []
        ctx_sum = 0
        gset = info.get("gset") or set()
        accepted_c = 0
        for slot, _r in sel:
            n = int(acc[slot]) + 1
            emitted_total += n
            accepted_total += int(acc[slot])
            if slot in gset:
                accepted_c += int(acc[slot])
            cur = info["cursors"].get(slot, 0)
            spans.append((cur, n))
            ctx_sum += min(cur, w) if w else cur
        self.spec_accepted += accepted_total
        self.spec_accepted_c += accepted_c
        self._observe_tput(emitted_total, dt)
        self._phases["step"].observe(dt)
        if self.anomaly is not None:
            self.anomaly.observe("step", dt * 1e3)
            # per-STEP acceptance (not the cumulative gauge — a drift
            # detector needs the instantaneous rate): accepted over the
            # positions this verify actually proposed (ys is [S, W],
            # W-1 drafts + 1 bonus per selected lane)
            self.anomaly.observe(
                "spec_accept",
                accepted_total / max(1, len(sel) * (ys.shape[1] - 1)),
            )
        # per-token cadence the accepted spans actually delivered
        per_tok = dt / max(1.0, emitted_total / max(1, len(sel)))
        self._phases["decode_step"].observe(per_tok)
        self._observe_mfu(
            "decode",
            tokens=emitted_total,
            flops=self._mfu_mod.spec_verify_flops(self._costs, spans),
            bytes_moved=(
                self._costs.params_bytes
                + ctx_sum * self._costs.kv_bytes_per_ctx_token
            ),
            dt=dt,
        )
        if self.metrics is not None:
            if accepted_total - accepted_c:
                self.metrics.increment_counter(
                    "app_llm_spec_accepted_total",
                    by=float(accepted_total - accepted_c),
                    model=self.label, constrained="0",
                )
            if accepted_c:
                self.metrics.increment_counter(
                    "app_llm_spec_accepted_total",
                    by=float(accepted_c), model=self.label, constrained="1",
                )
            self.metrics.set_gauge(
                "app_llm_spec_accept_rate",
                self.spec_accepted / max(1, self.spec_proposed),
                model=self.label,
            )
            self.metrics.record_histogram(
                "app_llm_step_seconds", dt, model=self.label,
                **self._role_labels,
            )
            wave = 1 << max(0, len(sel) - 1).bit_length() if sel else 0
            # chunk label "v{W}" marks verify walls: per-token cost here
            # includes the whole W-wide pass, not a chunk's K serial steps
            self.metrics.record_histogram(
                "app_llm_decode_step_seconds", per_tok,
                model=self.label, chunk=f"v{info['W']}", wave=str(wave),
                fused="0", **self._role_labels,
            )
        if self.goodput is not None:
            # verify is a dense [S, W] device pass: selected lanes own
            # their accepted span (+1 bonus) as useful and the rejected
            # draft positions as spec_reject; unselected rows are padding
            lanes = []
            for slot, r in sel:
                a = int(acc[slot])
                use = min(a + 1, max(0, r.max_new_tokens - r.emitted))
                cl = {"useful": use}
                if a + 1 - use > 0:
                    cl["padding"] = a + 1 - use
                rej = info["n_draft"].get(slot, 0) - a
                if rej > 0:
                    cl["spec_reject"] = rej
                lanes.append((r, cl))
            pad = ys.shape[1] * (ys.shape[0] - len(sel))
            if pad > 0:
                lanes.append((None, {"padding": pad}))
            self.goodput.observe("verify", info["t0"], now, lanes)
        from .spec import SPEC_EMA_ALPHA

        with self._lock:
            for slot, r in sel:
                a = int(acc[slot])
                toks = [int(t) for t in ys[slot, : a + 1]]
                if self.metrics is not None:
                    self.metrics.record_histogram(
                        "app_llm_spec_tokens_per_step", float(len(toks)),
                        model=self.label,
                    )
                if r.span is not None and r.finish_reason is None:
                    self._phase_span(
                        r, "llm.decode", info["t0"], now,
                        attrs={
                            "llm.spec_draft": info["n_draft"].get(slot, 0),
                            "llm.spec_accepted": a,
                            "llm.slot": slot,
                        },
                    )
                nd = info["n_draft"].get(slot, 0)
                if nd:
                    r._spec_ema = (
                        (1 - SPEC_EMA_ALPHA) * r._spec_ema
                        + SPEC_EMA_ALPHA * (a / nd)
                    )
                    r._spec_plain = 0
                # optimistic-pipeline reconciliation: a fully-correct
                # prediction pops its span off the pending stream; any
                # misprediction invalidates the whole remainder (later
                # in-flight verifies still emit VALID tokens — their
                # drafts were simply mis-aimed and will be rejected)
                p = info["pred"].get(slot, [])
                if toks == p and r._spec_pending[: len(p)] == p:
                    r._spec_pending = r._spec_pending[len(p):]
                else:
                    r._spec_pending = []
                r._spec_inflight = max(0, r._spec_inflight - 1)
                self._emit_to(r, slot, toks, now)
            self._processing = None  # same acquisition as the emits
        if self.logger is not None:
            self._flush_wide_events()

    def _abort_all(self) -> None:
        jnp = self._jnp
        with self._lock:
            now = time.perf_counter()
            for slot, r in enumerate(self._slot_req):
                if r is not None and r.finish_reason is None:
                    r.finish_reason = "cancelled"
                    self._observe_finish(r, now)
                    r.out.put(None)
                self._slot_req[slot] = None
            self._active = jnp.zeros((self.slots,), bool)
            self._temps = jnp.zeros((self.slots,), jnp.float32)

    def _schedule_loop(self) -> None:
        jnp = self._jnp
        try:
            while not self._stop:
                if self.faults.take("replica_kill", self.label) is not None:
                    # terminal chaos: the whole-replica death the failover
                    # and supervisor paths exist for (NOT routed through
                    # the per-iteration recovery below — a kill is final)
                    self._count_fault("replica_kill")
                    self._die("fault injection: replica_kill")
                    break
                if self._poison_fault():
                    break  # tagged payload killed this replica (terminal)
                try:
                    self._run_sched_work()
                    if self.kv.paged:
                        # paged-pool housekeeping, in dependency order:
                        # publish finished session turns (needs the
                        # blocks), return retired slots' blocks, spill
                        # cold sessions past their device budget
                        self._kv_session_flush()
                        self._kv_sweep()
                        self._kv_session_spill()
                    did = self._admit()
                    if self._stop:
                        break
                    with self._lock:
                        depth = sum(
                            1 for e in self._inflight
                            if e[0] in ("chunk", "step", "verify")
                        )
                        if (
                            self._processing is not None
                            and self._processing[0] in ("chunk", "step", "verify")
                        ):
                            depth += 1
                        needed = self._needed_steps()
                        prefilling = bool(self._prefilling)
                    stepped = False
                    if prefilling and depth < self.lookahead:
                        # one unified step per pass: prefill chunks packed
                        # to the token budget, decode riding along — the
                        # loop comes straight back for the next step
                        stepped = self._dispatch_step()
                        if stepped:
                            depth += 1
                            needed = max(0, needed - self.decode_chunk)
                    did_v = False
                    chunk_ok = True
                    if self.speculative:
                        # Speculative regime policy: decode advances
                        # through fused verify steps whenever anything
                        # drafts (verifies pipeline to lookahead depth —
                        # see _dispatch_verify). When a CLEAN-pipe
                        # drafting attempt yields nothing — cold slots,
                        # or every request backed off — the engine buys a
                        # bounded burst of plain chunks (_spec_hold), the
                        # chunk pipeline hiding the fetch RTT a 1-wide
                        # verify would expose; at the end of the burst
                        # the pipe drains and speculation re-probes, so a
                        # stream whose tail turns repetitive recovers.
                        # Chunks and verifies never interleave: a chunk
                        # advances EVERY device-active slot from the
                        # on-device tail and would double-advance a
                        # verify's slots.
                        with self._lock:
                            inflight_kinds = {
                                e[0] for e in self._inflight
                            }
                            if self._processing is not None:
                                inflight_kinds.add(self._processing[0])
                            ver_fly = "verify" in inflight_kinds
                            dec_fly = bool(
                                inflight_kinds & {"chunk", "step", "verify"}
                            )
                        if (
                            not stepped and depth < self.lookahead
                            and self._spec_hold <= 0
                        ):
                            did_v = self._dispatch_verify()
                            if not did_v and not dec_fly:
                                # clean attempt, nothing drafted: plain
                                # decode burst before the next probe
                                self._spec_hold = self._SPEC_REPROBE_CHUNKS
                        chunk_ok = (
                            not ver_fly and not did_v and self._spec_hold > 0
                        )
                    want = 0
                    if chunk_ok:
                        want = min(
                            -(-needed // self.decode_chunk),
                            self.lookahead - depth,
                        )
                        for _ in range(max(0, want)):
                            needed = max(0, needed - self._dispatch(needed))
                            if self.speculative:
                                self._spec_hold -= 1
                    if not did and not stepped and not did_v and want <= 0:
                        self._kick.wait(timeout=0.005)
                        self._kick.clear()
                except Exception as e:  # noqa: BLE001 — engine must not die silently
                    if self.logger is not None:
                        self.logger.error(f"LLM engine step failed: {e!r}")
                    self._recover_all()
                    if self.logger is not None:
                        self._flush_wide_events()
                    time.sleep(0.1)
        finally:
            # Anything that escapes the per-iteration handler (BaseException,
            # a failure inside recovery itself) would otherwise leave a
            # zombie engine: queued requests hang until stream timeout and
            # the replica router keeps feeding it. Die loudly instead.
            if not self._stop:
                self._die("scheduler thread exited unexpectedly")

    def _die(self, why: str, lock_timeout: float | None = None) -> None:
        """Terminal failure: mark the engine dead (alive() -> False,
        submit() refuses), hand every RECOVERABLE request to the failover
        hook when one is wired (ReplicatedLLMEngine re-dispatches them to
        a live replica), then end-of-stream everything else — occupants,
        in-flight snapshots, the waiting list, and the admit queue — so
        no consumer blocks until its stream timeout.

        Idempotent (the watchdog, the scheduler's finally, and the
        collector's finally can race). `lock_timeout` bounds the lock
        acquisition for callers that suspect the lock is WEDGED under a
        hung device call (the watchdog): on timeout the engine is still
        marked dead — the router stops feeding it and the supervisor
        replaces it — but the drain is skipped and the hung entries'
        consumers hit their stream timeout (nothing else is safe to do
        from outside the critical section)."""
        with self._die_guard:
            if self._died:
                return
            self._died = True
        self._stop = True
        self.died_reason = why
        self._fail_sched_work()  # pending handoff work cannot run now
        if self.logger is not None:
            self.logger.error(f"LLM engine died: {why}")
        # black-box bundle FIRST, while the corpse is still warm — the
        # rescue/drain below mutates the very state the bundle captures
        # (slots empty, gauges zero, requests re-homed). The reason
        # prefix classifies the trigger: watchdog/numerical/poison trips
        # each rate-limit independently of generic engine deaths.
        from .flightrec import classify_die_reason

        self._incident(
            classify_die_reason(why), reason=why,
            lock_timeout=2.0 if lock_timeout is None
            else min(2.0, lock_timeout),
        )
        if lock_timeout is None:
            acquired = self._lock.acquire()
        else:
            acquired = self._lock.acquire(timeout=lock_timeout)
        rescued: list[GenRequest] = []
        if acquired:
            try:
                if self.failover_hook is not None:
                    rescued = self._extract_recoverable()
                try:
                    self._recover_all()
                except Exception:  # noqa: BLE001 — draining must not re-raise
                    pass
                self._drain_pending()
            finally:
                self._lock.release()
        elif self.logger is not None:
            self.logger.error(
                "LLM engine lock wedged while dying; marked dead without "
                "drain (in-flight consumers will hit their stream timeout)"
            )
        self._zero_state_gauges()
        self._teardown_profiling()
        # the bundle above was this engine's LAST dump: a dead engine
        # must not write further bundles. The record ring deliberately
        # survives (unlike close()) — it is the post-mortem's evidence.
        self.blackbox.close()
        try:
            # a dead engine's pool/radix/session bookkeeping (and its
            # resident-bytes gauges) must not survive it — same contract
            # as close(); device buffers free with the engine object
            self.kv.close()
        except Exception:  # noqa: BLE001 — dying must not re-raise
            pass
        if self.ledger is not None:
            self.ledger.set_active(self.label, set())  # see close()
        self._kick.set()
        if acquired:
            with self._work_cv:
                self._work_cv.notify_all()
        if rescued:
            # OUTSIDE the lock: the hook submits into OTHER engines and
            # must not nest their locks under ours
            try:
                self.failover_hook(rescued)
            except Exception as e:  # noqa: BLE001 — rescue must terminate
                if self.logger is not None:
                    self.logger.error(f"failover hook failed: {e!r}")
                for r in rescued:
                    if r.finish_reason == "failover":
                        r.finish_reason = "error"
                        r.out.put(None)

    def _extract_recoverable(self) -> list[GenRequest]:
        """Collect every request a replacement replica could finish —
        slotted, mid-prefill, riding an in-flight snapshot, waiting, or
        still in the admit queue — and mark each finish_reason="failover"
        so the regular die-drain paths (which close only requests with
        finish_reason None) walk straight past them. The failover hook
        clears the marker on re-dispatch or replaces it with "error".
        Call with the lock held. Returned in submit order (ids are a
        process-global monotone counter)."""
        rescued: dict[int, GenRequest] = {}
        # Requests IN FLIGHT at death (slotted, mid-prefill, or riding a
        # device snapshot) are implicated in it for the router's
        # poison-request quarantine; queued-only bystanders are not — a
        # request that merely waited behind a poison payload twice must
        # not be refused service for it.
        inflight_ids: set[int] = set()

        def take(r: GenRequest | None, inflight: bool = False) -> None:
            if r is not None and r.finish_reason is None and not r.cancelled:
                rescued[r.id] = r
                if inflight:
                    inflight_ids.add(r.id)

        for r in self._slot_req:
            take(r, inflight=True)
        entries = list(self._inflight)
        if self._processing is not None:
            entries.append(self._processing)
        for e in entries:
            for r in self._entry_requests(e):
                take(r, inflight=True)
        for r in self._prefilling:
            take(r, inflight=True)
        for r in self._waiting:
            take(r)
        # the admit queue must be drained here (not left to
        # _drain_pending, which would close rescued members): pulled
        # non-recoverable entries get their end-of-stream immediately
        now = time.perf_counter()
        while True:
            try:
                r = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if r is None:
                continue
            if r.finish_reason is None and not r.cancelled:
                take(r)
            elif r.finish_reason is None:
                r.finish_reason = "cancelled"
                self._observe_finish(r, now)
                r.out.put(None)
        out = [rescued[i] for i in sorted(rescued)]
        for r in out:
            r.finish_reason = "failover"
            if r.id in inflight_ids:
                r.deaths += 1
        return out

    def _recover_all(self) -> None:
        """Full-stop recovery: close every request reachable from in-flight
        snapshots or slots, discard queued work, and reset device state.
        ONE critical section (callable from either thread): releasing the
        lock mid-way would let the other thread admit fresh requests into
        slots/tail that the remainder of the reset then clobbers."""
        with self._lock:
            # virtually-freed requests live ONLY in the snapshots
            # being discarded — close them before clearing, or
            # their consumers never see an end-of-stream
            orphans: set = set()
            entries = list(self._inflight)
            if self._processing is not None:
                entries.append(self._processing)
            for entry in entries:
                orphans.update(self._entry_requests(entry))
            now = time.perf_counter()
            for r in orphans:
                if r.finish_reason is None:
                    r.finish_reason = "cancelled"
                    self._observe_finish(r, now)
                    r.out.put(None)
            self._inflight.clear()
            self._processing = None
            self._prefilling.clear()  # occupants are closed by _abort_all
            self._fetch_fail_streak = 0  # fresh state deserves a fresh count
            self._admitting = 0  # an aborted wave never reaches its slots
            self._tail = self._jnp.zeros((self.slots,), self._jnp.int32)
            self._abort_all()

    def _collect_loop(self) -> None:
        try:
            self._collect_loop_inner()
        finally:
            if not self._stop:  # see _schedule_loop's finally
                self._die("collector thread exited unexpectedly")

    def _collect_loop_inner(self) -> None:
        while True:
            with self._work_cv:
                while not self._inflight and not self._stop:
                    self._work_cv.wait(timeout=0.1)
                if not self._inflight:
                    if self._stop:
                        return
                    continue
                # TTFT: serve prefill entries (first tokens of fresh
                # requests) before queued chunk fetches. Only ordering
                # WITHIN a request matters, and a request's prefill always
                # precedes its chunks in the deque — jumping a prefill
                # ahead of other requests' chunk tokens is safe. The jump
                # is rationed to one per processed chunk: unbounded
                # priority starves chunk emission whenever fresh arrivals
                # keep the prefill queue non-empty (measured: p50 3x worse
                # at 50 QPS).
                idx = 0
                if not self._jumped:
                    idx = next(
                        (
                            i for i, e in enumerate(self._inflight)
                            if self._jump_safe(e)
                        ),
                        0,
                    )
                if idx:
                    entry = self._inflight[idx]
                    del self._inflight[idx]
                    self._jumped = True
                else:
                    entry = self._inflight.popleft()
                    if entry[0] in ("chunk", "verify") or (
                        entry[0] == "step" and entry[5]
                    ):
                        self._jumped = False
                self._processing = entry
            try:
                with self._hb_fetch.beat(f"fetch:{entry[0]}"):
                    self._fault_latency()  # chaos: a wedged transfer
                    self._process_entry(entry)
                self._fetch_fail_streak = 0
            except Exception as e:  # noqa: BLE001
                if self.logger is not None:
                    self.logger.error(f"LLM engine fetch failed: {e!r}")
                self._fetch_fail_streak += 1
                if self._fetch_fail_streak >= self._FETCH_FAIL_LIMIT:
                    # persistent device-side failure: make-up chunks would
                    # fail too, so sparing slot occupants just busy-loops
                    # dispatch/fail forever — full reset like the
                    # scheduler's error path
                    self._fetch_fail_streak = 0
                    self._recover_all()
                else:
                    self._close_unreachable(entry)
            finally:
                with self._lock:
                    self._processing = None
            self._kick.set()
            if self.logger is not None:
                self._flush_wide_events()

    @staticmethod
    def _jump_safe(entry: tuple) -> bool:
        """May the collector serve this entry ahead of older in-flight
        entries? Prefill waves always: they carry ONLY fresh requests'
        first tokens, and a request's prefill precedes its chunks in the
        deque. A step entry with finishing rows carries first tokens too
        — but ALSO the piggybacked decode chunk for every already-active
        slot, and those slots' earlier tokens may sit in the bypassed
        entries; jumping it would permute an active request's stream. So
        a step jumps only when its decode part serves no one beyond its
        own finishing rows (cold prefill ramp — exactly when TTFT-jumping
        pays; finishing rows can't appear in older entries because they
        were not prefill_done at those dispatches)."""
        if entry[0] == "prefill":
            return True
        if entry[0] != "step" or not entry[2]:
            return False
        fin = {r for _j, _s, r in entry[2]}
        return all(r is None or r in fin for r in entry[4])

    @staticmethod
    def _entry_requests(entry: tuple):
        """Requests carried by an in-flight entry (all entry kinds)."""
        if entry[0] == "prefill":
            return [r for _, r in entry[2] if r is not None]
        if entry[0] == "verify":
            return [r for _s, r in entry[3]]
        if entry[0] == "step":
            out = [r for _j, _s, r in entry[2]]
            if entry[4] is not None:
                out.extend(r for r in entry[4] if r is not None)
            return out
        return [r for r in entry[2] if r is not None]

    def _close_unreachable(self, failed: tuple) -> None:
        """A failed fetch permanently loses its entry's tokens. A request
        in its snapshot can still reach max_new_tokens only if it owns a
        slot (the scheduler sees its stalled emitted count and dispatches
        make-up chunks) or if SURVIVING queued entries carry enough tokens
        to finish it. A virtually-freed predecessor with neither would
        never see end-of-stream and block its consumer until the stream
        timeout — close exactly those. (Survivors' streams carry a token
        gap where the lost entry's tokens were; loss is inherent to a
        failed fetch, and termination is the contract being kept.)"""
        with self._lock:
            # clear under the SAME acquisition as the closes: the failed
            # entry's tokens are lost, and leaving it visible lets the
            # scheduler count them in _inflight_steps and virtually free a
            # slot on the strength of tokens that will never arrive
            self._processing = None
            lost = set(self._entry_requests(failed))
            lost.difference_update(self._slot_req)
            if not lost:
                return
            cover: dict = {}
            for e in self._inflight:
                if e[0] == "verify":
                    # mirror _inflight_steps' guaranteed-minimum: a verify
                    # covers at least the bonus token per selected slot
                    for r in self._entry_requests(e):
                        if r in lost:
                            cover[r] = cover.get(r, 0) + 1
                    continue
                if e[0] == "step":
                    # mirror _inflight_steps (finishes and snapshot
                    # iterated SEPARATELY — a finishing row appears in
                    # both, and visiting it twice would credit 2K+2
                    # instead of K+1, spuriously skipping the close and
                    # hanging the consumer): a finishing row carries its
                    # first token plus the piggybacked decode; a
                    # snapshot-only rider carries the decode steps alone
                    fin = {r for _j, _s, r in e[2]}
                    for r in fin:
                        if r in lost:
                            cover[r] = cover.get(r, 0) + e[5] + 1
                    if e[4] is not None:
                        for r in e[4]:
                            if r is not None and r in lost and r not in fin:
                                cover[r] = cover.get(r, 0) + e[5]
                    continue
                n = 1 if e[0] == "prefill" else e[3]
                for r in self._entry_requests(e):
                    if r in lost:
                        cover[r] = cover.get(r, 0) + n
            now = time.perf_counter()
            for r in lost:
                if (
                    r.finish_reason is None
                    and r.emitted + cover.get(r, 0) < r.max_new_tokens
                ):
                    r.finish_reason = "cancelled"
                    self._observe_finish(r, now)
                    r.out.put(None)


class ReplicatedLLMEngine:
    """Data-parallel replicated serving: N independent LLMEngine replicas —
    one per chip (or per tensor-parallel submesh) — behind a per-request
    router (SURVEY §2.8 row 1: "Replicated serving across chips;
    per-replica dispatch of batched requests").

    Each replica owns its full weight copy, KV cache, and scheduler, so
    replicas never synchronize: DP serving scales throughput linearly the
    way the reference scales by stateless pod replication (README.md:25),
    but within one process over the local device set. Composition with TP:
    pass `meshes=[(mesh, param_specs), ...]` and each replica runs
    tensor-parallel over its own submesh — dp x tp serving from one API.

    Routing: "least_loaded" (default) weighs each replica by its QUEUED
    TOKENS (prompt remainder + expected decode, LLMEngine.load_tokens) —
    a 128-token prompt is 16x the device work of an 8-token prompt, and
    counting requests instead piles long-prompt traffic onto one replica;
    occupant/queue count breaks ties. "round_robin" is stateless and
    optimal for uniform work.

    The public surface mirrors LLMEngine (submit/generate/stats/close), so
    ctx.tpu().llm(name) callers cannot tell one replica from many.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        replicas: int | None = None,
        devices: list | None = None,
        meshes: list | None = None,
        router: str = "least_loaded",
        logger=None,
        supervise: bool = True,
        version: str = "v1",
        failover_retries: int | None = None,
        fleet_max_queue_tokens: int | None = None,
        retry_budget_per_s: float | None = None,
        retry_budget_burst: float | None = None,
        poison_deaths: int | None = None,
        canary: bool | None = None,
        health_ledger=None,
        **engine_kw,
    ):
        import jax
        import os as _os

        if router not in ("least_loaded", "round_robin"):
            raise ValueError(f"unknown router {router!r}")
        self.router = router
        self._rr = itertools.count()
        specs: list[dict]
        if meshes is not None:
            specs = [{"mesh": m, "param_specs": s} for m, s in meshes]
        else:
            if devices is None:
                devices = jax.devices()[: replicas or 1]
            if replicas is not None and len(devices) < replicas:
                raise ValueError(
                    f"need {replicas} devices for {replicas} replicas, "
                    f"have {len(devices)}"
                )
            specs = [{"device": d} for d in devices]
        if not specs:
            raise ValueError("no replicas configured")
        if logger is not None:
            logger.info(
                f"replicated LLM serving: {len(specs)} replicas, "
                f"router={router}, supervise={supervise}"
            )
        # Rebuild inputs retained for the supervisor: a dead replica is
        # reconstructed from the SAME cfg/params/spec on the same
        # device/submesh. Holding `params` keeps the host copy alive for
        # the process lifetime — the price of restartability (pass
        # supervise=False to opt out and drop nothing extra: the engines
        # hold their device copies either way).
        self.logger = logger
        self.metrics = engine_kw.get("metrics")
        self.label = engine_kw.pop("kv_label", "llm")
        engine_kw.pop("version", None)  # fleet-owned; per-slot below
        # -- versioned weight registry (docs/advanced-guide/rollouts.md) --
        # The fleet retains (cfg, params) PER VERSION: the active version
        # serves, a staged version is shifted in replica-by-replica by
        # the rollout controller, and a rollback rebuilds from whichever
        # retained version the slot should run. _slot_versions tracks
        # what each replica slot serves RIGHT NOW (mixed mid-rollout).
        self.version = str(version)
        self._versions: dict[str, tuple] = {self.version: (cfg, params)}
        self._slot_versions = [self.version] * len(specs)
        # slots the rollout controller owns right now: the supervisor
        # must not race it by rebuilding a replica the controller just
        # drained/closed on purpose
        self._rollout_hold: set[int] = set()
        self._rollout = None  # active/last RolloutController
        self._rollout_lock = threading.Lock()
        self._versions_seen: set[str] = set()  # every gauge row ever written
        # shadow-probe source: the last few REAL prompts, mirrored onto a
        # rollout candidate before it is admitted to routing (sanity, not
        # token equality — versions legitimately differ)
        self._shadow_ring: deque = deque(maxlen=8)
        # Session affinity (docs/advanced-guide/kv-cache.md#sessions):
        # the paged session tier is PER-REPLICA state, so a conversation
        # routed to a different replica pays a full re-prefill. Remember
        # which replica holds each session and prefer it while it
        # accepts; bounded LRU so abandoned conversations cannot grow
        # the map forever.
        self._session_affinity: OrderedDict[str, int] = OrderedDict()
        self._session_affinity_cap = 65536
        self._specs = specs
        self._engine_kw = engine_kw
        if failover_retries is None:
            failover_retries = int(
                _os.environ.get("TPU_LLM_FAILOVER_RETRIES", "2")
            )
        self.failover_retries = max(0, failover_retries)
        self.failovers = 0  # requests re-dispatched off a dead replica
        self.failover_errors = 0  # rescues that found no live replica
        self._draining = False
        # -- fleet overload control (docs/advanced-guide/overload.md) -----
        # ONE fairness ledger shared by every replica: the virtual token
        # counters pool across the fleet, so least-served ordering holds
        # no matter which replica a client's requests land on. Retained
        # in _engine_kw, so supervised rebuilds rejoin the same ledger.
        from .resilience import FairLedger, RetryBudget

        fq = engine_kw.get("fair_queuing")
        if fq is None:
            # same precedence as LLMEngine: an explicit kwarg beats the
            # env (otherwise TPU_LLM_FAIR=0 would silently skip the
            # SHARED ledger while each replica still built its own —
            # fleet fairness degraded to per-replica with no signal)
            fq = _os.environ.get("TPU_LLM_FAIR", "1") != "0"
        if fq:
            # NOT setdefault(key, FairLedger(pop(...))): the value
            # expression would evaluate eagerly, discarding fair_weights
            # (and a throwaway ledger) whenever a fair_ledger was also
            # passed — weights must land on whichever ledger is used
            weights = engine_kw.pop("fair_weights", None)
            if engine_kw.get("fair_ledger") is None:
                engine_kw["fair_ledger"] = FairLedger(weights)
            elif weights:
                for c, w in weights.items():
                    engine_kw["fair_ledger"].set_weight(c, w)
        self.ledger = engine_kw.get("fair_ledger")
        # ONE usage meter shared by every replica (the fair-ledger
        # pattern): per-tenant chip-second/token windows pool across the
        # fleet, so quota enforcement and the usage endpoint see the
        # tenant's total rate no matter which replica admitted the
        # request. Retained in _engine_kw for supervised rebuilds.
        gp_on = engine_kw.get("goodput")
        if gp_on is None:
            gp_on = _os.environ.get("TPU_LLM_GOODPUT", "1") not in ("", "0")
        if gp_on and engine_kw.get("usage_meter") is None:
            from .goodput import UsageMeter

            win = engine_kw.get("usage_window_s")
            if win is None:
                win = float(
                    _os.environ.get("TPU_LLM_USAGE_WINDOW_S", "") or 60.0
                )
            engine_kw["usage_meter"] = UsageMeter(window_s=float(win))
        self.usage = engine_kw.get("usage_meter")
        # Fleet admission cap: reject at the summed queued-token estimate
        # across accepting replicas instead of piling onto the last
        # healthy engine (0 disables; per-engine max_queue still applies)
        if fleet_max_queue_tokens is None:
            fleet_max_queue_tokens = int(
                _os.environ.get("TPU_LLM_FLEET_MAX_QUEUE_TOKENS", "0") or 0
            )
        self.fleet_max_queue_tokens = max(0, int(fleet_max_queue_tokens))
        # batch-class headroom factor: batch work sheds at this fraction
        # of the fleet cap, so the LAST slice of fleet queue capacity is
        # reserved for interactive traffic — shed the reservoir before
        # the latency-sensitive class ever sees a 429
        # (docs/advanced-guide/overload.md + batch-inference.md)
        self.fleet_batch_factor = min(1.0, max(0.0, float(
            _os.environ.get("TPU_LLM_FLEET_BATCH_FACTOR", "0.8") or 0.8
        )))
        self.fleet_rejected = 0
        # Retry budget: router-side retries (failover re-dispatch,
        # replica death between pick and submit) draw from a token
        # bucket, so overload can never amplify into a retry storm — the
        # same pathology the inter-service circuit breaker guards
        # (gofr_tpu.service).
        if retry_budget_per_s is None:
            retry_budget_per_s = float(
                _os.environ.get("TPU_LLM_RETRY_BUDGET_PER_S", "1.0") or 0.0
            )
        if retry_budget_burst is None:
            retry_budget_burst = float(
                _os.environ.get("TPU_LLM_RETRY_BUDGET_BURST", "10") or 0.0
            )
        self.retry_budget = RetryBudget(retry_budget_per_s, retry_budget_burst)
        self.retry_budget_exhausted = 0
        # -- device health + poison quarantine (resilience.health;
        # docs/advanced-guide/resilience.md) ------------------------------
        # One ledger for the fleet: replica deaths and rebuild failures
        # are classified and billed to the device the engine ran on, and
        # a device that accumulates TPU_LLM_DEVICE_QUARANTINE_FAILURES
        # inside the window is quarantined — the supervisor then rebuilds
        # the slot elastically on an alternate healthy device (or parks
        # it, visibly, when none exists).
        from .resilience import DeviceHealthLedger, spec_device_key

        self.health = (
            health_ledger if health_ledger is not None
            else DeviceHealthLedger(
                metrics=self.metrics, model=self.label, logger=logger,
            )
        )
        self._device_keys = [spec_device_key(s) for s in specs]  # home devices
        self._current_keys = list(self._device_keys)  # where each slot runs NOW
        # Poison-request quarantine: a request in flight across this many
        # replica deaths is refused further failover (finish_reason
        # "poison" -> 500/INTERNAL) — one payload's blast radius is
        # bounded to poison_deaths replicas, never the fleet. 0 disables.
        if poison_deaths is None:
            poison_deaths = int(_os.environ.get("TPU_LLM_POISON_DEATHS", "2") or 0)
        self.poison_deaths = max(0, int(poison_deaths))
        self.poisoned = 0  # requests refused failover as poison
        # Canary gate: a rebuilt/reintegrated replica must reproduce the
        # fixed greedy probe (token-compared against a healthy replica's
        # cached output when one exists) before it re-enters routing.
        if canary is None:
            canary = _os.environ.get("TPU_LLM_CANARY", "1") != "0"
        self._canary_enabled = bool(canary)
        # healthy replicas' probe tokens, PER MODEL VERSION — different
        # weights legitimately produce different canary streams, so a v2
        # candidate must never be token-compared against the v1 reference
        self._canary_ref: dict[str, list[int]] = {}
        # Fleet adapter registry (gofr_tpu.lora): host copies of every
        # registered adapter checkpoint, so a rebuilt/shifted replica
        # re-stages the SAME tenant set its peers serve (_build_replica).
        # Insertion-ordered: re-staging replays loads oldest-first, which
        # reproduces the pool's LRU layout closely enough for tests.
        self._adapters_host: dict[str, dict] = {}
        # build replicas concurrently: XLA releases the GIL while compiling,
        # so N warmups overlap instead of serializing construction N-fold.
        # On any failure, close the replicas that DID come up — each holds
        # scheduler threads plus device-resident weights and KV cache that
        # would otherwise leak with no handle to free them.
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(specs)) as pool:
            futures = [
                pool.submit(self._build_replica, i)
                for i in range(len(specs))
            ]
            engines, first_err = [], None
            for f in futures:
                try:
                    engines.append(f.result())
                except Exception as e:  # noqa: BLE001
                    first_err = first_err or e
        if first_err is not None:
            for e in engines:
                e.close()
            raise first_err
        self.engines = engines
        self._observe_versions()
        # incident seam (gofr_tpu.flightrec): a quarantine trip dumps a
        # black-box bundle from a live replica — the dying replica's own
        # _die bundle captures ITS corpse, this one captures the fleet
        # context (ledger state, which device, surviving capacity)
        self.health.on_quarantine = lambda device, why: self.incident(
            "quarantine", reason=f"device {device} quarantined ({why})"
        )
        self.supervisor = None
        if supervise:
            from .resilience import ReplicaSupervisor

            self.supervisor = ReplicaSupervisor(
                self,
                interval_s=float(
                    _os.environ.get("TPU_LLM_SUPERVISOR_INTERVAL_S", "0.5")
                ),
                backoff_s=float(
                    _os.environ.get("TPU_LLM_RESTART_BACKOFF_S", "1.0")
                ),
                backoff_max_s=float(
                    _os.environ.get("TPU_LLM_RESTART_BACKOFF_MAX_S", "30")
                ),
            )

    def _build_replica(
        self, i: int, spec: dict | None = None, version: str | None = None,
    ) -> "LLMEngine":
        """Construct (and warm) replica slot i from its retained spec —
        the same path at first build, at supervised restart, and at a
        rollout shift. ``spec`` overrides the home placement for elastic
        rebuilds (the supervisor passes an alternate healthy device when
        the home device is quarantined); ``version`` overrides the
        slot's current version (the rollout controller passes the target
        version on a shift and the retained old version on a rollback).
        Wires the failover hook so the new replica's deaths rescue
        in-flight work too. Per-replica kv label: N replicas sharing one
        label set would clobber each other's resident-bytes gauges."""
        from .resilience import InjectedFault, default_injector, spec_device_key

        spec = self._specs[i] if spec is None else spec
        version = self._slot_versions[i] if version is None else version
        cfg, params = self._versions[version]
        inj = self._engine_kw.get("fault_injector") or default_injector()
        key = spec_device_key(spec)
        if inj.take("device_sick", key) is not None:
            # chaos: a persistently sick chip — construction (param
            # placement / warmup) fails on this device, as an HBM or ICI
            # fault would, until the spec is disarmed or exhausted
            if self.logger is not None:
                self.logger.warn(f"fault injection: device_sick fired on {key}")
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_faults_injected_total",
                    point="device_sick", model=self.label,
                )
            raise InjectedFault(f"device_sick: build refused on {key}")
        eng = LLMEngine(
            cfg, params, logger=self.logger,
            kv_label=f"{self.label}/r{i}", version=version, **spec,
            **self._engine_kw,
        )
        eng.failover_hook = self._failover
        # re-stage the fleet's registered adapters (gofr_tpu.lora): a
        # supervised restart or rollout shift must come back serving the
        # same tenant set as its peers — a replica with an empty pool
        # would 404 every adapter-routed request the router lands on it
        if getattr(eng, "lora_slots", 0):
            for name, rec in list(self._adapters_host.items()):
                try:
                    eng.load_adapter(
                        name, rec["adapter"], version=rec["version"],
                        alpha=rec["alpha"], fair_weight=rec["fair_weight"],
                    )
                except Exception as ex:  # noqa: BLE001
                    if self.logger is not None:
                        self.logger.warn(
                            f"adapter {name!r} re-stage failed on rebuilt "
                            f"replica: {ex}"
                        )
        return eng

    def _spec_for_rebuild(self, i: int) -> tuple[dict, str] | None:
        """Placement policy for rebuilding slot i, consulting the device
        ledger: the home device/submesh when it is usable (healthy, or
        in probation — the canary gate guards the probe) and not
        occupied by another live replica; otherwise an alternate
        same-platform device that is usable and unoccupied, or — for
        tensor-parallel submeshes — an alternate SAME-SIZE submesh of
        usable, unoccupied chips (elastic submesh placement;
        docs/advanced-guide/sharded-serving.md). None = park: only when
        no placement exists anywhere."""
        home = self._specs[i]
        hkey = self._device_keys[i]
        used = {
            self._current_keys[j]
            for j, e in enumerate(self.engines)
            if j != i and e.alive()
        }
        if self.health.usable(hkey) and hkey not in used:
            return home, hkey
        dev = home.get("device")
        if dev is None:
            return self._alternate_submesh_spec(i, home)
        import jax

        from .resilience import device_key

        platform = getattr(dev, "platform", None)
        for d in jax.devices():
            if getattr(d, "platform", None) != platform:
                continue
            k = device_key(d)
            if k == hkey or k in used or not self.health.usable(k):
                continue
            return {"device": d}, k
        return None

    def _alternate_submesh_spec(self, i: int, home: dict) -> tuple[dict, str] | None:
        """Elastic SUBMESH placement: rebuild slot i's tensor-parallel
        replica on an alternate same-size, same-shape submesh of usable,
        unoccupied chips. The quarantined home submesh used to park its
        slot unconditionally (PR 7); now it parks only when no such
        submesh exists — the chips of every other live replica and the
        members of every quarantined submesh are excluded, the alternate
        mesh reuses the home mesh's axis names/shape, and the home's
        param_specs carry over unchanged (PartitionSpecs are
        mesh-independent)."""
        mesh = home.get("mesh")
        if mesh is None:
            return None
        try:
            homedevs = list(mesh.devices.flat)
        except AttributeError:  # duck-typed test meshes: nothing to re-place
            return None
        if not homedevs:
            return None
        import jax
        import numpy as np

        from .resilience import device_key, spec_device_key, split_device_key

        n = len(homedevs)
        platform = getattr(homedevs[0], "platform", None)
        # chips occupied by OTHER live replicas, wherever elastic
        # rebuilds currently place them
        used: set[str] = set()
        for j, e in enumerate(self.engines):
            if j != i and e.alive():
                used.update(split_device_key(self._current_keys[j]))
        # members of every quarantined ledger unit: a submesh trips as a
        # unit, so its chips are individually suspect until it
        # reintegrates (probation members stay eligible — the canary
        # gate judges the rebuild, exactly like single-device probation)
        sick: set[str] = set()
        for key, row in self.health.snapshot()["devices"].items():
            if row["state"] == "quarantined":
                sick.update(split_device_key(key))
        cands = [
            d for d in jax.devices()
            if getattr(d, "platform", None) == platform
            and device_key(d) not in used
            and device_key(d) not in sick
        ]
        if len(cands) < n:
            return None  # park: no same-size submesh of usable chips
        new_mesh = jax.sharding.Mesh(
            np.asarray(cands[:n]).reshape(mesh.devices.shape),
            mesh.axis_names,
        )
        spec = dict(home, mesh=new_mesh)
        return spec, spec_device_key(spec)

    def _canary_check(self, replacement: "LLMEngine") -> tuple[bool, str]:
        """Gate a rebuilt replica before it enters routing: the fixed
        greedy probe, token-compared against a healthy SAME-VERSION
        replica's cached output when the fleet has (ever had) one, else
        against completeness/vocabulary checks
        (resilience.health.canary_check). References are cached per
        model version — greedy decode is deterministic per
        params+config, so a version's reference never goes stale, and a
        rollout candidate on new weights is never compared against the
        old version's tokens."""
        if not self._canary_enabled:
            return True, "disabled"
        from .resilience.health import CANARY_MAX_NEW, CANARY_PROMPT, canary_check

        v = replacement.version
        ref = self._canary_ref.get(v)
        has_peer = False
        if ref is None:
            for e in self.engines:
                if e is replacement or not e.accepting() or e.version != v:
                    continue
                has_peer = True
                try:
                    ref = e.generate(
                        list(CANARY_PROMPT), max_new_tokens=CANARY_MAX_NEW,
                        temperature=0.0, eos_token=-1, probe=True,
                    )
                    if len(ref) == CANARY_MAX_NEW:
                        self._canary_ref[v] = ref
                        break
                    ref = None
                except Exception:  # noqa: BLE001 — a sick reference is no reference
                    ref = None
        ok, detail, toks = canary_check(replacement, ref)
        if ok and ref is None and not has_peer:
            # TRULY no healthy same-version replica existed (the first
            # replica of a staged version, or a fleet-wide outage): the
            # gated candidate's own passing output seeds the reference
            # for future canaries of this version. When a peer exists
            # but its reference fetch failed transiently (saturated,
            # draining race), do NOT self-seed — caching an unverified
            # candidate's tokens would poison the permanent reference
            # and canary-reject every honest rebuild after it; the next
            # canary simply retries the peer.
            self._canary_ref[v] = toks
        return ok, detail

    # -- model lifecycle (resilience.rollout;
    # docs/advanced-guide/rollouts.md) --------------------------------------
    def deploy(
        self,
        cfg=None,
        params=None,
        *,
        version: str | None = None,
        bake_s: float | None = None,
        shadow_probes: int | None = None,
        drain_timeout_s: float | None = None,
    ) -> dict:
        """Stage a new model version and shift the running fleet onto it
        with zero downtime: the rollout controller drains one replica at
        a time, rebuilds it on the new weights through the supervisor's
        ``_build_replica`` seam, gates it with the canary probe plus a
        shadow-traffic replay, admits it to routing, and watches a bake
        window afterwards — any regression (replica death, numerical
        trip, canary/shadow failure, request-error delta) rolls every
        upgraded replica back to the retained old params. The fleet
        always ends fully on ONE version.

        ``params`` are validated against ``cfg`` (structure, shapes,
        dtypes — models.checkpoint.validate_params) BEFORE any device
        transfer: a bad checkpoint is a 4xx at the admin route, never a
        dead replica. Returns the rollout snapshot immediately; progress
        is visible in stats()/debug_state()["rollout"] and the
        app_llm_rollout_* metrics."""
        from .models.checkpoint import validate_params
        from .resilience.rollout import (
            RolloutController,
            RolloutError,
            RolloutInProgress,
        )

        if params is None:
            raise RolloutError("deploy() needs params (the new weights)")
        active_cfg, _ = self._versions[self.version]
        cfg = active_cfg if cfg is None else cfg
        validate_params(params, cfg)  # typed 4xx before anything moves
        with self._rollout_lock:
            if self._rollout is not None and self._rollout.active():
                raise RolloutInProgress(
                    f"rollout to {self._rollout.to_version!r} already in "
                    f"progress (state {self._rollout.state})"
                )
            if self._draining:
                raise EngineDraining("fleet draining; refusing rollout")
            if version is None:
                version = self._derive_version()
            if version in self._versions:
                raise RolloutError(
                    f"model version {version!r} already exists "
                    f"(known: {sorted(self._versions)})"
                )
            self._versions[version] = (cfg, params)
            ctl = RolloutController(
                self, version, bake_s=bake_s, shadow_probes=shadow_probes,
                drain_timeout_s=drain_timeout_s,
            )
            self._rollout = ctl
            ctl.start()
        return ctl.snapshot()

    def _derive_version(self) -> str:
        """Next free label in the conventional v<N> sequence (used when
        deploy() is not given an explicit version)."""
        import re

        nums = [
            int(m.group(1))
            for v in self._versions
            for m in [re.match(r"^v(\d+)$", v)] if m
        ]
        n = (max(nums) + 1) if nums else (len(self._versions) + 1)
        while f"v{n}" in self._versions:
            n += 1
        return f"v{n}"

    def version_counts(self) -> dict[str, int]:
        """Live replicas per model version (mixed only mid-rollout)."""
        counts: dict[str, int] = {}
        for e in self.engines:
            if e.alive():
                counts[e.version] = counts.get(e.version, 0) + 1
        return counts

    def _observe_versions(self) -> None:
        """Keep ``app_llm_model_version_info`` truthful at fleet level:
        value = live replicas serving that version, and every version
        label the fleet has ever exported is re-written (stale rows from
        a completed or rolled-back version must read 0, not their last
        live value — the dead-engine gauge bug class)."""
        if self.metrics is None:
            return
        counts = self.version_counts()
        for v in set(self._versions) | set(counts) | self._versions_seen:
            self._versions_seen.add(v)
            self.metrics.set_gauge(
                "app_llm_model_version_info", float(counts.get(v, 0)),
                model=self.label, version=v,
            )

    def rollout_state(self) -> dict | None:
        """Snapshot of the active (or most recent) rollout, None if a
        deploy was never staged."""
        ctl = self._rollout
        return None if ctl is None else ctl.snapshot()

    # -- multi-tenant adapters (gofr_tpu.lora;
    # docs/advanced-guide/multi-tenancy.md) --------------------------------
    def load_adapter(
        self, name: str, adapter: dict, *, version: str = "v1",
        alpha: float | None = None, fair_weight: float | None = None,
    ) -> int:
        """Stage ``adapter`` on every live replica and retain a host copy
        so rebuilt/shifted replicas re-stage it (_build_replica). Returns
        the number of replicas staged; raises when none took it (a
        partial fleet serves — the router only lands adapter traffic on
        replicas that resolved the name, via submit failover)."""
        errs: list[Exception] = []
        done = 0
        for e in self.engines:
            if not e.alive():
                continue
            try:
                e.load_adapter(
                    name, adapter, version=version, alpha=alpha,
                    fair_weight=fair_weight,
                )
                done += 1
            except Exception as ex:  # noqa: BLE001
                errs.append(ex)
        if not done:
            raise errs[0] if errs else EngineStoppedError("all replicas dead")
        self._adapters_host[name] = {
            "adapter": adapter, "version": str(version), "alpha": alpha,
            "fair_weight": fair_weight,
        }
        return done

    def publish_adapter(self, staging: str, name: str) -> int:
        """Commit a staged hot-load on every live replica (atomic
        per-replica; in-flight requests drain on their old gid). Returns
        replicas switched."""
        done = 0
        for e in self.engines:
            if not e.alive():
                continue
            try:
                e.publish_adapter(staging, name)
                done += 1
            except Exception:  # noqa: BLE001
                pass  # replica without the staging name: nothing to commit
        rec = self._adapters_host.pop(staging, None)
        if rec is not None:
            self._adapters_host[name] = rec
        return done

    def evict_adapter(self, name: str) -> int:
        """Retire ``name`` fleet-wide (idle gids free now, busy ones
        drain as zombies). Returns replicas that held it."""
        self._adapters_host.pop(name, None)
        done = 0
        for e in self.engines:
            if not e.alive():
                continue
            try:
                e.evict_adapter(name)
                done += 1
            except KeyError:
                pass
        return done

    def adapters(self) -> dict:
        """Fleet adapter view: the registry's names plus the first live
        replica's pool snapshot (replicas converge on the same resident
        set; gids may differ per replica and are reported per-pool)."""
        lead = next((e for e in self.engines if e.alive()), None)
        snap = lead.adapters() if lead is not None else {
            "slots": 0, "resident": {}, "zombies": [],
            "evictions": 0, "swaps": 0,
        }
        return {**snap, "registered": sorted(self._adapters_host)}

    # -- routing -----------------------------------------------------------
    def _pick(
        self,
        exclude: set | frozenset = frozenset(),
        version: str | None = None,
    ) -> "LLMEngine":
        """Route among replicas that ACCEPT work — alive and not
        draining. A replica whose scheduler or collector thread died
        (LLMEngine._die) hands its queued requests to the failover hook;
        the router's job is to stop feeding it new ones. ``version``
        restricts the candidate set to replicas serving that model
        version — the failover path's mid-stream pin (a stream must
        never carry tokens from two versions)."""
        live = [
            e for e in self.engines
            if e.accepting() and id(e) not in exclude
            and (version is None or e.version == version)
        ]
        if not live:
            if any(
                e.alive() for e in self.engines
                if version is None or e.version == version
            ):
                raise EngineDraining("all replicas draining")
            raise EngineStoppedError(
                "all replicas dead" if version is None
                else f"no live replica serves model version {version!r}"
            )
        if self.router == "round_robin" or len(live) == 1:
            return live[next(self._rr) % len(live)]
        # token-weighted least-loaded: queued device work, not request
        # count — load() breaks ties so an idle replica still wins when
        # token estimates momentarily agree
        return min(live, key=lambda e: (e.load_tokens(), e.load()))

    # -- LLMEngine surface -------------------------------------------------
    def submit(self, req: GenRequest) -> GenRequest:
        # keep the budget gauge live: written only on retry events it
        # would stick at its post-burst low forever while the bucket
        # quietly refilled — a permanent false alarm for operators
        # alerting on "0 = retries disabled"
        self._observe_retry_budget()
        # Fleet-level admission: reject at the SUMMED queued-token
        # estimate across accepting replicas. Without this, per-replica
        # caps let a dying fleet funnel the whole offered load onto the
        # last healthy engine — the cap the fleet was sized for, not the
        # cap one replica was.
        if self.fleet_max_queue_tokens > 0:
            queued = sum(
                e.load_tokens() for e in self.engines if e.accepting()
            )
            # batch sheds FIRST: the throughput class hits a lowered cap
            # (fleet_batch_factor) so the top slice of queue capacity
            # stays reserved for interactive traffic under pressure
            cap = self.fleet_max_queue_tokens
            if req.priority == "batch":
                cap = int(cap * self.fleet_batch_factor)
            if queued >= cap:
                self.fleet_rejected += 1
                if self.metrics is not None:
                    # its own series, NOT app_llm_sheds_predicted_total:
                    # a queue-cap rejection and a predicted-wait shed are
                    # different causes and operators alert on them
                    # differently
                    self.metrics.increment_counter(
                        "app_llm_fleet_rejected_total", model=self.label
                    )
                raise EngineOverloaded(
                    f"fleet queue full ({queued} >= {cap} queued tokens"
                    + (" at batch-class headroom)" if cap
                       < self.fleet_max_queue_tokens else ")"),
                    retry_after=self._fleet_retry_after(queued),
                )
        # Error classification (docs/advanced-guide/overload.md):
        # - EngineStoppedError / EngineDraining are RETRYABLE — the
        #   replica died or began draining between pick and submit, and
        #   another replica can serve the request. Retries past the first
        #   attempt draw from the retry budget (no retry storms).
        # - EngineOverloaded is NON-RETRYABLE: the router already picked
        #   the least-loaded replica, so every other replica is at least
        #   as loaded — walking the fleet would turn one client's 429
        #   into fleet-wide overload amplification.
        # Bounded: the supervisor may swap replacements in mid-loop, so
        # the exclusion set alone is not a terminator.
        tried: set[int] = set()
        first_err: Exception | None = None
        if req.adapter and req.adapter not in self._adapters_host:
            # fast 404 for a name NO replica can serve (fleet registry
            # miss + no direct per-engine load): walking the fleet would
            # burn retry budget on an error every replica repeats
            if not any(
                req.adapter in e.adapters()["resident"]
                for e in self.engines if e.alive()
            ):
                raise UnknownAdapterError(
                    req.adapter, self._adapters_host
                )
        # session affinity: the replica holding this conversation's KV
        # (resident or host-spilled) serves the next turn as a prefix
        # hit; any other replica re-prefills the whole history. Falls
        # back to normal routing when the remembered replica is gone or
        # not accepting — sessions degrade, never error.
        prefer = None
        sid = req.session_id
        if sid:
            eid = self._session_affinity.get(sid)
            if eid is not None:
                prefer = next(
                    (e for e in self.engines if id(e) == eid), None
                )
                if prefer is not None and not prefer.accepting():
                    prefer = None
        for attempt in range(2 * len(self.engines) + 2):
            if attempt > 0 and not self.retry_budget.take():
                self.retry_budget_exhausted += 1
                self._observe_retry_budget()
                raise first_err  # budget spent: surface the original error
            if attempt > 0:
                self._observe_retry_budget()
            if prefer is not None and id(prefer) not in tried:
                eng = prefer
            else:
                eng = self._pick(exclude=tried)
            try:
                out = eng.submit(req)
            except (
                EngineStoppedError, EngineDraining, UnknownAdapterError,
            ) as e:
                # UnknownAdapterError is retryable HERE only: a replica
                # mid-rebuild may not have re-staged the adapter yet,
                # while its peers serve it (the registry fast-path above
                # already 404'd names nobody holds)
                first_err = first_err or e
                tried.add(id(eng))
                continue
            if sid:
                self._session_affinity.pop(sid, None)
                self._session_affinity[sid] = id(eng)
                while len(self._session_affinity) > self._session_affinity_cap:
                    self._session_affinity.popitem(last=False)
            # shadow-probe source (rollouts): remember a bounded prefix
            # of real accepted prompts; a rollout candidate replays a few
            # before admission (deque append is thread-safe, O(1))
            self._shadow_ring.append(tuple(req.prompt_tokens[:32]))
            return out
        raise first_err or EngineStoppedError("all replicas dead")

    def _fleet_retry_after(self, queued_tokens: int) -> float:
        """Retry-After for a fleet-level rejection: excess backlog over
        the cap, priced at the fleet's pooled measured throughput (1 s
        floor when no replica has an estimate yet)."""
        tput = self.throughput_tok_s()
        if tput is None:
            return 1.0
        excess = max(0, queued_tokens - self.fleet_max_queue_tokens)
        return max(0.5, excess / tput) if excess else 1.0

    def _observe_retry_budget(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_retry_budget_remaining",
                self.retry_budget.remaining(), model=self.label,
            )

    # -- in-flight failover (gofr_tpu.resilience) --------------------------
    def _failover(self, reqs: list[GenRequest]) -> None:
        """A dying replica's rescued requests, re-dispatched to the live
        survivors. Each continuation re-seeds its prompt with everything
        already emitted (prompt + history), so the consumer's stream
        resumes exactly where it left off — no duplicate and no missing
        token, token-identical for greedy decodes (sampled decodes
        continue with fresh randomness). Errors surface only when the
        per-request retry budget is spent or no live replica remains."""
        # ONE overload-wait window shared by the whole batch: a saturated
        # survivor must cost the rescue ~5 s total, not 5 s per rescued
        # request serially on the dying engine's thread
        batch_deadline = time.perf_counter() + 5.0
        for r in reqs:
            if self.poison_deaths and r.deaths >= self.poison_deaths:
                # poison-request quarantine: this payload was in flight
                # for poison_deaths replica deaths — the router stops
                # treating it as an innocent bystander and errors it to
                # its caller (500/INTERNAL via PoisonedRequestError)
                # instead of letting it kill another replica
                self.poisoned += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_llm_poison_requests_total", model=self.label
                    )
                if self.logger is not None:
                    self.logger.error(
                        f"poison quarantine: request {r.id} implicated in "
                        f"{r.deaths} replica deaths; failover refused"
                    )
                r.finish_reason = "poison"
                if r.span is not None and r.span.end_ns == 0:
                    r.span.set_attribute("llm.finish_reason", "poison")
                    r.span.set_status("ERROR")
                    r.span.end()
                r.out.put(None)
                continue
            r.retries += 1
            placed = False
            budget_ok = True
            if r.retries <= self.failover_retries:
                # failover re-dispatch is a router-side retry: it draws
                # from the same budget as submit-time retries, so a
                # crash-looping replica under overload cannot multiply
                # its queued work across the survivors forever
                budget_ok = self.retry_budget.take()
                if not budget_ok:
                    self.retry_budget_exhausted += 1
                self._observe_retry_budget()
            if budget_ok and r.retries <= self.failover_retries:
                # goodput replay marker: the survivor re-prefills work
                # the dead replica already did — its prefill progress,
                # or the whole grown prompt once history folds in
                replay_to = r.prefill_pos
                if r.history:
                    r.prompt_tokens = list(r.prompt_tokens) + r.history
                    r.history = []
                    replay_to = len(r.prompt_tokens)
                r._replay_pos = max(r._replay_pos, replay_to)
                # reset engine-owned scheduling state; consumer-facing
                # state (out queue, emitted, span) carries over
                r.finish_reason = None
                r.phase = "queued"
                r.prefill_pos = 0
                r.prefill_done = False
                r.slot = None
                r._rows_hi = 0
                r._prefill_t0 = None
                r._load_acct = 0
                tried: set[int] = set()
                # Mid-stream version pin (docs/advanced-guide/rollouts.md):
                # a request that already emitted tokens continues ONLY on
                # a replica serving the same model version — resuming the
                # continuation prompt on different weights would splice
                # two models' tokens into one stream (silent corruption:
                # the bytes look plausible and the status is 200). A
                # request with nothing emitted may restart anywhere; its
                # stream is still single-version by construction.
                pin = r.engine_version if r.emitted > 0 else None
                # A momentarily FULL live replica is not a dead one:
                # excluding it would error rescued work while capacity
                # exists seconds later (the overload+death case failover
                # exists for). Overloads wait-and-retry inside the shared
                # window; only stopped/draining replicas are excluded.
                first_try = True
                while first_try or time.perf_counter() < batch_deadline:
                    first_try = False
                    try:
                        eng = self._pick(exclude=tried, version=pin)
                    except (EngineStoppedError, EngineDraining):
                        if (
                            pin is not None
                            and self.logger is not None
                            and any(e.accepting() for e in self.engines)
                        ):
                            self.logger.error(
                                f"failover: request {r.id} pinned to model "
                                f"version {pin} mid-stream and no live "
                                f"replica serves it; erroring instead of "
                                f"mixing versions"
                            )
                        break
                    try:
                        eng.submit(r)
                        placed = True
                        break
                    except (EngineStoppedError, EngineDraining):
                        tried.add(id(eng))
                    except EngineOverloaded:
                        time.sleep(0.05)
                    except ValueError:
                        break  # continuation no longer fits the cache
            if placed:
                self.failovers += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_llm_failovers_total", model=self.label
                    )
                if self.logger is not None:
                    self.logger.warn(
                        f"failover: request {r.id} re-dispatched "
                        f"(retry {r.retries}/{self.failover_retries})"
                    )
            else:
                self.failover_errors += 1
                if self.metrics is not None:
                    self.metrics.increment_counter(
                        "app_llm_failover_errors_total", model=self.label
                    )
                r.finish_reason = "error"
                if r.span is not None and r.span.end_ns == 0:
                    r.span.set_attribute("llm.finish_reason", "error")
                    r.span.set_status("ERROR")
                    r.span.end()
                r.out.put(None)

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def load(self) -> int:
        return sum(e.load() for e in self.engines)

    def load_tokens(self) -> int:
        return sum(e.load_tokens() for e in self.engines)

    def throughput_tok_s(self) -> float | None:
        """Pooled measured throughput across live replicas (None until
        any replica has a window) — the fleet's share of the scale-out
        admission signal (docs/advanced-guide/scale-out.md)."""
        tput = sum(e._tput_ema or 0.0 for e in self.engines if e.alive())
        return tput if tput > 1e-9 else None

    def predicted_wait_s(self) -> float | None:
        """Fleet predicted queue wait: summed queued tokens over pooled
        measured throughput (the per-engine estimate, lifted across
        replicas)."""
        tput = self.throughput_tok_s()
        if tput is None:
            return None
        return self.load_tokens() / tput

    def stats(self) -> dict:
        per = [e.stats() for e in self.engines]
        out = {
            "replicas": len(per),
            "replicas_alive": sum(e.alive() for e in self.engines),
            "router": self.router,
            "draining": self._draining,
            # model lifecycle (docs/advanced-guide/rollouts.md)
            "version": self.version,
            "versions": self.version_counts(),
            "rollout": self.rollout_state(),
            "disconnect_cancels": sum(
                s.get("disconnect_cancels", 0) for s in per
            ),
            "failovers": self.failovers,
            "failover_errors": self.failover_errors,
            "restarts": self.supervisor.restarts if self.supervisor else 0,
            # device health + poison quarantine (resilience.health)
            "poisoned": self.poisoned,
            "devices_quarantined": self.health.quarantined_count(),
            "replicas_parked": (
                self.supervisor.parked_count() if self.supervisor else 0
            ),
            "replicas_failed": (
                self.supervisor.failed_count() if self.supervisor else 0
            ),
            # fleet overload control (docs/advanced-guide/overload.md)
            "preemptions": sum(s.get("preemptions", 0) for s in per),
            "sheds_predicted": sum(s.get("sheds_predicted", 0) for s in per),
            "fleet_rejected": self.fleet_rejected,
            "fleet_max_queue_tokens": self.fleet_max_queue_tokens,
            "retry_budget_remaining": round(self.retry_budget.remaining(), 2),
            "retry_budget_exhausted": self.retry_budget_exhausted,
            "fairness": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            # multi-tenant adapters (gofr_tpu.lora)
            "adapters": self.adapters(),
            # fleet speculative-decoding totals (per-replica in per_replica)
            "spec": {
                "enabled": any(
                    (s.get("spec") or {}).get("enabled") for s in per
                ),
                "proposed": sum(
                    (s.get("spec") or {}).get("proposed", 0) for s in per
                ),
                "accepted": sum(
                    (s.get("spec") or {}).get("accepted", 0) for s in per
                ),
            },
            "slots": sum(s["slots"] for s in per),
            "active": sum(s["active"] for s in per),
            "waiting": sum(s["waiting"] for s in per),
            "max_seq_len": per[0]["max_seq_len"],
            "decode_chunk": per[0]["decode_chunk"],
            "per_replica": per,
            # fleet-wide phase percentiles: pooled raw windows, not an
            # average of per-replica percentiles (which has no meaning)
            "phases": self._merged_phases(),
            "mfu": self._merged_mfu(),
            # fleet chip-time attribution (gofr_tpu.goodput): summed
            # per-replica ledgers; ratio recomputed from the pooled sums
            "goodput": self._merged_goodput(),
        }
        prefixes = [
            s["kvcache"]["prefix"] for s in per if s["kvcache"].get("prefix")
        ]
        if prefixes:  # fleet-wide prefix-cache totals (per-replica in per_replica)
            out["kvcache_prefix"] = {
                key: sum(p.get(key, 0) for p in prefixes)
                for key in ("hits", "misses", "partial_hits", "evictions",
                            "resident_bytes")
            }
        return out

    def _merged_phases(self) -> dict:
        from .metrics import summarize_window

        merged: dict[str, list[float]] = {}
        for e in self.engines:
            for name, w in e._phases.items():
                merged.setdefault(name, []).extend(w.values())
        return {name: summarize_window(vs) for name, vs in merged.items()}

    def _merged_mfu(self) -> dict:
        """Fleet utilization, same shape as LLMEngine.stats()['mfu'] so
        consumers (bench's _mfu_block, dashboards) never branch on the
        engine kind: pooled raw MFU/roofline/token-rate windows (the
        no-averaging-percentiles rule of _merged_phases)."""
        from .metrics import summarize_window

        lead = self.engines[0]
        out: dict = {
            "chips": sum(e._n_chips for e in self.engines),
            "peak_flops_per_chip": lead._peak_flops,
            "hbm_bw_per_chip": lead._hbm_bw,
            "params": lead._costs.params,
            "flops_per_token": lead._costs.matmul_flops_per_token,
        }
        for key in ("prefill", "decode"):
            out[key] = summarize_window(
                [v for e in self.engines for v in e._mfu_windows[key].values()]
            )
        out["tokens_per_second_per_chip"] = summarize_window(
            [v for e in self.engines for v in e._tok_chip_window.values()]
        )
        roofline = {
            key: summarize_window([
                v for e in self.engines
                for v in e._roofline_windows[key].values()
            ])
            for key in ("prefill", "decode")
        }
        roofline["bound"] = lead._mfu_mod.classify_bound(roofline["decode"]["p50"])
        out["roofline"] = roofline
        return out

    def debug_state(self) -> dict:
        return {
            "router": self.router,
            "replicas": len(self.engines),
            "replicas_alive": sum(e.alive() for e in self.engines),
            "draining": self._draining,
            # model lifecycle (docs/advanced-guide/rollouts.md)
            "version": self.version,
            "versions_retained": sorted(self._versions),
            "slot_versions": list(self._slot_versions),
            "rollout": self.rollout_state(),
            "failovers": self.failovers,
            "failover_errors": self.failover_errors,
            "failover_retries": self.failover_retries,
            "fleet_rejected": self.fleet_rejected,
            "fleet_max_queue_tokens": self.fleet_max_queue_tokens,
            "retry_budget": {
                "remaining": round(self.retry_budget.remaining(), 2),
                "rate_per_s": self.retry_budget.rate,
                "burst": self.retry_budget.burst,
                "exhausted": self.retry_budget_exhausted,
            },
            "fairness": (
                self.ledger.snapshot() if self.ledger is not None else None
            ),
            "supervisor": (
                self.supervisor.snapshot()
                if self.supervisor is not None else None
            ),
            "health": self.health.snapshot(),
            "devices": {
                "home": list(self._device_keys),
                "current": list(self._current_keys),
            },
            "poison_deaths": self.poison_deaths,
            "poisoned": self.poisoned,
            "canary": self._canary_enabled,
            "phases": self._merged_phases(),
            "slo": self._merged_slo(),
            "goodput": self._merged_goodput(),
            "usage": (
                self.usage.snapshot() if self.usage is not None else None
            ),
            "per_replica": [e.debug_state() for e in self.engines],
        }

    def _merged_goodput(self) -> dict | None:
        """Fleet goodput pooling: chip-second sums are additive across
        replicas; the useful fraction recomputes from the pooled sums
        (never average per-replica ratios)."""
        from .goodput import pool_goodput

        snaps = [
            e.goodput.snapshot() for e in self.engines
            if e.goodput is not None
        ]
        return pool_goodput(snaps) if snaps else None

    def usage_state(self) -> dict:
        """Windowed per-tenant usage + pooled goodput for the
        /.well-known/debug/usage endpoint (chargeback export). The meter
        is SHARED across replicas, so tenant windows are fleet-local
        totals already — no per-replica summing needed."""
        usage = (
            self.usage.snapshot() if self.usage is not None
            else {"window_s": None, "tenants": {}}
        )
        return {
            "replicas": len(self.engines),
            "goodput": self._merged_goodput(),
            "quota": (
                self.engines[0].quota.snapshot()
                if self.engines and self.engines[0].quota is not None
                else None
            ),
            "quota_sheds": sum(e.quota_sheds for e in self.engines),
            **usage,
        }

    def set_tenant_quota(self, tenant: str, tok_s: float | None) -> None:
        """Fleet quota update: every replica's gate enforces against the
        SHARED usage meter, so the ceiling is a fleet-total rate.
        Retained in _engine_kw so supervised rebuilds rejoin with the
        same quota table (the shared-ledger discipline)."""
        q = self._engine_kw.setdefault("quotas", {})
        if tok_s is None or tok_s <= 0:
            q.pop(tenant, None)
        else:
            q[tenant] = float(tok_s)
        for e in self.engines:
            e.set_tenant_quota(tenant, tok_s)

    def _merged_slo(self) -> dict | None:
        """Fleet SLO pooling: summed goodput, max-burn-across-replicas
        (the hottest replica gates health — same semantics as
        gauge_total over the per-replica fast-burn gauge)."""
        from .metrics.slo import pool_snapshots

        snaps = [
            e.slo.snapshot() for e in self.engines if e.slo is not None
        ]
        return pool_snapshots(snaps) or None

    # -- incident flight recorder (gofr_tpu.flightrec; docs/advanced-
    # guide/incident-debugging.md) ----------------------------------------
    def incident(self, trigger: str, *, reason: str = "") -> str | None:
        """Dump one black-box bundle from the first live replica —
        fleet-level triggers (quarantine, rollout rollback) need a
        witness that still has state; a dying replica dumps its own
        bundle from _die before this could reach it."""
        for e in self.engines:
            if e.alive():
                return e._incident(trigger, reason=reason)
        return None

    def replay(self, record_or_id, *, timeout: float = 120.0) -> dict:
        """Deterministic replay across the fleet: locate the flight
        record on any replica (dead ones keep their rings for exactly
        this), then re-execute on a live replica pinned to the record's
        model version — cross-version replays compare nothing."""
        from .flightrec import find_record, replay_record

        rec = record_or_id
        if not isinstance(rec, dict):
            rec, _owner = find_record(self, int(record_or_id))
            if rec is None:
                return {
                    "id": record_or_id,
                    "error": "no flight record with that id on any replica",
                }
        want = rec.get("model_version")
        for e in self.engines:
            if e.alive() and (not want or e.version == want):
                return replay_record(e, rec, timeout=timeout)
        return {
            "id": rec.get("id"),
            "error": f"no live replica serves version {want!r} for replay",
        }

    def drain(self) -> None:
        """Fleet drain: stop the supervisor from rebuilding (the process
        is going down), close admission on every live replica, let
        in-flight work finish. The app lifecycle polls drained()."""
        self._draining = True
        for e in self.engines:
            if e.alive():
                e.drain()

    def drained(self) -> bool:
        # aliveness FIRST: e.drained() on a watchdog-killed replica whose
        # lock is wedged under a hung device call would block the drain
        # poll forever (the deadline could never fire)
        return all(not e.alive() or e.drained() for e in self.engines)

    def close(self) -> None:
        self._draining = True  # a rebuild racing close must not be routed
        if self._rollout is not None:
            # a mid-shift controller must stop BEFORE the engines close:
            # it would otherwise race the teardown rebuilding replicas
            # into a fleet that no longer exists
            self._rollout.close()
        if self.supervisor is not None:
            self.supervisor.close()
        for e in self.engines:
            e.close()
        if self.metrics is not None:
            # a closed fleet must not keep exporting its last budget
            # level, capacity-degradation state, or model-version rows
            # (the dead-engine gauge bug class)
            for name in (
                "app_llm_retry_budget_remaining",
                "app_llm_devices_quarantined",
                "app_llm_replicas_parked",
                "app_llm_replicas_failed",
                "app_llm_rollout_state",
            ):
                self.metrics.set_gauge(name, 0.0, model=self.label)
            for v in set(self._versions) | self._versions_seen:
                self.metrics.set_gauge(
                    "app_llm_model_version_info", 0.0,
                    model=self.label, version=v,
                )
