"""LLM serving engine: slot-based continuous batching with token streaming.

The decode-serving core for BASELINE.json configs 3/5 (gRPC streaming
Gemma decode; multi-chip tensor-parallel serving). No counterpart in the
reference repo — this is the TPU-native replacement for its goroutine-per-
request model at the model-serving layer (SURVEY.md §7 hard part 5:
"continuous batching / slot-based scheduler is the real design problem").

Design (all shapes static; one compiled executable per op):

- **Slots.** A fixed decode batch of S slots with one persistent KV cache
  [n_layers, S, max_seq_len, hkv, hd] on device. Every decode step advances
  ALL slots in one `decode_step`; inactive slots are masked (their cursor is
  pinned to 0 so they never overflow and their tokens are discarded).
- **Admission.** Waiting requests are prefilled in length-bucketed batches
  (powers of two), then their KV rows are inserted into free slots via
  jitted dynamic_update_slice on the batch axis — the running decode batch
  never recompiles as traffic changes.
- **On-device sampling.** The decode wrapper samples (greedy or temperature)
  on device and returns only the S int32 token ids, so the host loop syncs
  one tiny transfer per step instead of a [S, vocab] logits matrix.
- **Streaming.** Each request owns a thread-safe queue; the engine thread
  pushes tokens as they decode; consumers iterate stream() (sync) or
  astream() (async) and detach by cancelling — a detached request just
  frees its slot, never stalling the batch (same contract as the TPU
  datasource batcher).

Tensor parallelism: pass mesh + param_specs; the slot cache is resharded by
GSPMD from the params' shardings (KV replicated under MQA, sharded when the
TP degree divides n_kv_heads) — identical code single-chip and multi-chip.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

__all__ = ["LLMEngine", "GenRequest"]

_EOS_DEFAULT = -1  # no EOS cut by default (random-weight models)


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token: int = _EOS_DEFAULT
    id: int = field(default_factory=itertools.count().__next__)

    def __post_init__(self):
        self.out: queue.Queue = queue.Queue()
        self.cancelled = False
        self.emitted = 0

    # -- consumption ------------------------------------------------------
    def stream(self, timeout: float = 60.0) -> Iterator[int]:
        """Yield token ids until the engine signals completion."""
        while True:
            item = self.out.get(timeout=timeout)
            if item is None:
                return
            yield item

    async def astream(self, timeout: float = 60.0):
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            item = await loop.run_in_executor(None, lambda: self.out.get(timeout=timeout))
            if item is None:
                return
            yield item

    def cancel(self) -> None:
        self.cancelled = True

    def tokens(self, timeout: float = 60.0) -> list[int]:
        return list(self.stream(timeout=timeout))


class LLMEngine:
    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 8,
        max_seq_len: int = 512,
        prefill_buckets: tuple[int, ...] = (16, 64, 128),
        mesh=None,
        param_specs: Any = None,
        logger=None,
        metrics=None,
        warmup: bool = True,
    ):
        import jax
        import jax.numpy as jnp

        from .models.transformer import decode_step, init_cache, prefill

        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = max_seq_len
        self.prefill_buckets = tuple(sorted(b for b in prefill_buckets if b <= max_seq_len))
        self.logger = logger
        self.metrics = metrics
        if mesh is not None and param_specs is not None:
            from .parallel.sharding import shard_params

            params = shard_params(params, mesh, param_specs)
        else:
            params = jax.device_put(params)
        self.params = params

        # -- jitted programs ---------------------------------------------
        def _prefill(params, tokens, lengths):
            last_logits, cache = prefill(params, cfg, tokens, lengths, max_seq_len)
            return last_logits, cache

        def _decode(params, tokens, cache, active, temps, rng):
            logits, new_cache = decode_step(params, cfg, tokens, cache)
            greedy = jnp.argmax(logits, axis=-1)
            sampled = jax.random.categorical(
                rng, logits / jnp.maximum(temps, 1e-4)[:, None], axis=-1
            )
            next_tok = jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)
            # inactive slots: pin cursor to 0 so they never hit the cache
            # edge (decode_step docstring precondition), discard their token
            new_length = jnp.where(active, new_cache.length, 0)
            return next_tok, new_cache._replace(length=new_length)

        def _insert(slot_cache, new_cache, slot_idx, row):
            # copy row `row` of a prefill cache into slot `slot_idx`
            k = jax.lax.dynamic_update_slice(
                slot_cache.k,
                jax.lax.dynamic_slice_in_dim(new_cache.k, row, 1, axis=1),
                (0, slot_idx, 0, 0, 0),
            )
            v = jax.lax.dynamic_update_slice(
                slot_cache.v,
                jax.lax.dynamic_slice_in_dim(new_cache.v, row, 1, axis=1),
                (0, slot_idx, 0, 0, 0),
            )
            length = jax.lax.dynamic_update_slice(
                slot_cache.length,
                jax.lax.dynamic_slice_in_dim(new_cache.length, row, 1, axis=0),
                (slot_idx,),
            )
            return slot_cache._replace(k=k, v=v, length=length)

        def _first_tok(last_logits, temps, rng):
            # same sampling semantics as _decode so token #1 honors the
            # request temperature (greedy only when temps == 0)
            greedy = jnp.argmax(last_logits, axis=-1)
            sampled = jax.random.categorical(
                rng, last_logits / jnp.maximum(temps, 1e-4)[:, None], axis=-1
            )
            return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)

        self._prefill = jax.jit(_prefill)
        self._first_tok = jax.jit(_first_tok)
        self._decode = jax.jit(_decode)
        self._insert = jax.jit(_insert)
        self._rng = jax.random.PRNGKey(0)
        self._split = jax.jit(lambda k: tuple(jax.random.split(k)))

        self.cache = init_cache(cfg, slots, max_seq_len)
        self.cache = self.cache._replace(length=jnp.zeros((slots,), jnp.int32))
        self._slot_req: list[GenRequest | None] = [None] * slots
        self._last_tok = np.zeros((slots,), np.int32)
        self._temps = np.zeros((slots,), np.float32)
        self._admit_q: queue.Queue[GenRequest | None] = queue.Queue()
        self._stop = False
        self._jnp = jnp
        self._jax = jax

        if warmup:
            self._warm()
        self._thread = threading.Thread(target=self._loop, name="llm-engine", daemon=True)
        self._thread.start()

    # -- public API -------------------------------------------------------
    def submit(self, req: GenRequest) -> GenRequest:
        if self._stop:
            raise RuntimeError("engine stopped")
        if len(req.prompt_tokens) >= self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_tokens)} tokens exceeds max_seq_len {self.max_seq_len}"
            )
        self._admit_q.put(req)
        return req

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "active": sum(r is not None for r in self._slot_req),
            "waiting": self._admit_q.qsize(),
            "max_seq_len": self.max_seq_len,
        }

    def close(self) -> None:
        self._stop = True
        self._admit_q.put(None)
        self._thread.join(timeout=10)

    # -- engine internals -------------------------------------------------
    def _warm(self) -> None:
        import jax

        jnp = self._jnp
        t0 = time.perf_counter()
        for b in self.prefill_buckets:
            toks = jnp.zeros((1, b), jnp.int32)
            lens = jnp.ones((1,), jnp.int32)
            _, c = self._prefill(self.params, toks, lens)
            self.cache = jax.block_until_ready(
                self._insert(self.cache, c, 0, 0)
            )
        self.cache = self.cache._replace(
            length=jnp.zeros((self.slots,), jnp.int32)
        )
        tok, self.cache = self._decode(
            self.params,
            jnp.zeros((self.slots,), jnp.int32),
            self.cache,
            jnp.zeros((self.slots,), bool),
            jnp.zeros((self.slots,), jnp.float32),
            self._rng,
        )
        jax.block_until_ready(tok)
        self.cache = self.cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        if self.logger is not None:
            self.logger.info(
                f"LLM engine warmed in {time.perf_counter() - t0:.1f}s "
                f"(buckets {self.prefill_buckets}, slots {self.slots})"
            )

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.max_seq_len

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _admit(self) -> None:
        """Pull waiting requests into free slots, prefilling per bucket."""
        jnp = self._jnp
        free = self._free_slots()
        pulled: list[GenRequest] = []
        while free[len(pulled):] :
            try:
                # Block briefly only when fully idle; stay hot otherwise.
                idle = all(r is None for r in self._slot_req) and not pulled
                req = self._admit_q.get(timeout=0.05) if idle else self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is None:
                self._stop = True
                break
            if req.cancelled:
                req.out.put(None)
                continue
            pulled.append(req)
        if not pulled:
            return
        # group by bucket to share prefill executions
        by_bucket: dict[int, list[GenRequest]] = {}
        for r in pulled:
            by_bucket.setdefault(self._bucket_for(len(r.prompt_tokens)), []).append(r)
        for bucket, reqs in by_bucket.items():
            # batch dim padded to a power of two: bounded executable count
            # (|buckets| x log2(slots) shapes), never a per-burst compile
            nb = 1
            while nb < len(reqs):
                nb *= 2
            toks = np.zeros((nb, bucket), np.int32)
            lens = np.ones((nb,), np.int32)  # pad rows: 1 token, discarded
            for j, r in enumerate(reqs):
                n = len(r.prompt_tokens)
                toks[j, :n] = r.prompt_tokens
                lens[j] = n
            t0 = time.perf_counter()
            last_logits, new_cache = self._prefill(self.params, toks, lens)
            temps = np.zeros((nb,), np.float32)
            for j, r in enumerate(reqs):
                temps[j] = r.temperature
            self._rng, sub = self._split(self._rng)
            first = np.asarray(
                self._first_tok(last_logits, self._jnp.asarray(temps), sub), np.int32
            )
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_tpu_stats", time.perf_counter() - t0,
                    model="llm", op=f"prefill_{bucket}",
                )
            for j, r in enumerate(reqs):
                slot = free.pop(0)
                self._slot_req[slot] = r
                self.cache = self._insert(self.cache, new_cache, slot, j)
                self._last_tok[slot] = first[j]
                self._temps[slot] = r.temperature
                self._emit(slot, int(first[j]))

    def _emit(self, slot: int, token: int) -> None:
        r = self._slot_req[slot]
        if r is None:
            return
        if r.cancelled:
            self._retire(slot)
            return
        r.out.put(token)
        r.emitted += 1
        if token == r.eos_token or r.emitted >= r.max_new_tokens:
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        r = self._slot_req[slot]
        if r is not None:
            r.out.put(None)
        self._slot_req[slot] = None
        self._temps[slot] = 0.0

    def _step(self) -> None:
        jnp = self._jnp
        active_mask = np.array([r is not None for r in self._slot_req])
        if not active_mask.any():
            return
        self._rng, sub = self._split(self._rng)
        t0 = time.perf_counter()
        tok, self.cache = self._decode(
            self.params,
            jnp.asarray(self._last_tok),
            self.cache,
            jnp.asarray(active_mask),
            jnp.asarray(self._temps),
            sub,
        )
        tok_host = np.asarray(tok)
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_stats", time.perf_counter() - t0, model="llm", op="decode"
            )
        self._last_tok = tok_host.copy()
        for slot in np.nonzero(active_mask)[0]:
            r = self._slot_req[slot]
            if r is None:
                continue
            if r.emitted + len(r.prompt_tokens) >= self.max_seq_len - 1:
                self._retire(int(slot))  # cache capacity guard
                continue
            self._emit(int(slot), int(tok_host[slot]))

    def _loop(self) -> None:
        while not self._stop:
            try:
                self._admit()
                self._step()
            except Exception as e:  # noqa: BLE001 — engine must not die silently
                if self.logger is not None:
                    self.logger.error(f"LLM engine step failed: {e!r}")
                for slot in range(self.slots):
                    self._retire(slot)
                time.sleep(0.1)
        # drain
        for slot in range(self.slots):
            self._retire(slot)
        while True:
            try:
                req = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.out.put(None)
