"""LLM serving engine: slot-based continuous batching with token streaming.

The decode-serving core for BASELINE.json configs 3/5 (gRPC streaming
Gemma decode; multi-chip tensor-parallel serving). No counterpart in the
reference repo — this is the TPU-native replacement for its goroutine-per-
request model at the model-serving layer (SURVEY.md §7 hard part 5:
"continuous batching / slot-based scheduler is the real design problem").

Design (all shapes static; a bounded set of compiled executables):

- **Slots.** A fixed decode batch of S slots with one persistent KV cache
  [n_layers, S, max_seq_len, hkv, hd] on device. Inactive slots are masked
  (their cursor stays pinned so they never overflow; their tokens are
  discarded on host).
- **Fused decode chunks.** Decode advances ALL slots K steps per dispatch
  (`decode_chunk`, a lax.scan over decode_step with on-device sampling).
  One host→device dispatch per K tokens amortizes dispatch latency — the
  dominant cost at decode's arithmetic intensity — and the engine keeps up
  to `lookahead` chunks in flight, chaining the next chunk's input tokens
  from the previous chunk's on-device output so the device never waits for
  host readback (the host processes chunk N while the device runs N+1).
- **Admission.** Waiting requests are prefilled in length-bucketed batches
  (powers-of-two capped at `admit_cap`), sampled on device (token #1 honors
  the request temperature), then their KV rows are copied into free slots
  via ONE jitted insert-many (scan of dynamic_update_slice) — the running
  decode batch never recompiles as traffic changes. Admission first drains
  in-flight chunks so the next dispatch sees a host-merged token vector.
- **On-device sampling.** Greedy or temperature sampling happens inside the
  chunk; the host syncs one [K, S] int32 array per chunk instead of logits.
- **Streaming.** Each request owns a thread-safe queue; the engine thread
  pushes tokens as chunks complete; consumers iterate stream() (sync) or
  astream() (async) and detach by cancelling — a detached request just
  frees its slot, never stalling the batch (same contract as the TPU
  datasource batcher).

Tensor parallelism: pass mesh + param_specs; the slot cache is resharded by
GSPMD from the params' shardings (KV replicated under MQA, sharded when the
TP degree divides n_kv_heads) — identical code single-chip and multi-chip.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

__all__ = ["LLMEngine", "GenRequest"]

_EOS_DEFAULT = -1  # no EOS cut by default (random-weight models)


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token: int = _EOS_DEFAULT
    id: int = field(default_factory=itertools.count().__next__)

    def __post_init__(self):
        self.out: queue.Queue = queue.Queue()
        self.cancelled = False
        self.emitted = 0

    # -- consumption ------------------------------------------------------
    def stream(self, timeout: float = 60.0) -> Iterator[int]:
        """Yield token ids until the engine signals completion."""
        while True:
            item = self.out.get(timeout=timeout)
            if item is None:
                return
            yield item

    async def astream(self, timeout: float = 60.0):
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            item = await loop.run_in_executor(None, lambda: self.out.get(timeout=timeout))
            if item is None:
                return
            yield item

    def cancel(self) -> None:
        self.cancelled = True

    def tokens(self, timeout: float = 60.0) -> list[int]:
        return list(self.stream(timeout=timeout))


class LLMEngine:
    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 32,
        max_seq_len: int = 512,
        prefill_buckets: tuple[int, ...] = (16, 64, 128),
        decode_chunk: int = 8,
        lookahead: int = 2,
        admit_cap: int = 8,
        mesh=None,
        param_specs: Any = None,
        logger=None,
        metrics=None,
        warmup: bool = True,
    ):
        import jax
        import jax.numpy as jnp

        from .models.transformer import decode_step, init_cache, prefill

        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = max_seq_len
        self.prefill_buckets = tuple(sorted(b for b in prefill_buckets if b <= max_seq_len))
        self.decode_chunk = decode_chunk
        self.lookahead = max(1, lookahead)
        self.admit_cap = min(admit_cap, slots)
        self.logger = logger
        self.metrics = metrics
        if mesh is not None and param_specs is not None:
            from .parallel.sharding import shard_params

            params = shard_params(params, mesh, param_specs)
        else:
            params = jax.device_put(params)
        self.params = params

        # -- jitted programs (one dispatch each) --------------------------
        topk = min(64, cfg.vocab_size)

        def _sample(logits, temps, key):
            """Greedy for temp==0; temperature sampling restricted to the
            top-k logits otherwise. Full-vocab categorical would generate
            batch x vocab Gumbel draws per step (millions of threefry
            rounds for a 256k vocab) and dominates decode time; top-k keeps
            the RNG work at batch x 64."""
            greedy = jnp.argmax(logits, axis=-1)
            topv, topi = jax.lax.approx_max_k(logits, topk)
            local = jax.random.categorical(
                key, topv / jnp.maximum(temps, 1e-4)[:, None], axis=-1
            )
            sampled = jnp.take_along_axis(topi, local[:, None], axis=1)[:, 0]
            return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)

        def _prefill_op(params, tokens, lengths, temps, rng):
            last_logits, cache = prefill(params, cfg, tokens, lengths, max_seq_len)
            rng, sub = jax.random.split(rng)
            first = _sample(last_logits, temps, sub)
            return first, cache, rng

        K = decode_chunk

        def _chunk_op(params, tokens, cache, active, temps, rng):
            """K decode steps fused in one executable. Slots advance only
            while `live` (active AND below cache capacity); frozen slots
            keep their cursor and re-emit their input token (discarded by
            the host)."""
            rng, sub = jax.random.split(rng)
            keys = jax.random.split(sub, K)

            def body(carry, key):
                tok, cache = carry
                live = active & (cache.length < max_seq_len)
                logits, new_cache = decode_step(params, cfg, tok, cache)
                nt = _sample(logits, temps, key)
                nt = jnp.where(live, nt, tok)
                new_len = jnp.where(live, new_cache.length, cache.length)
                return (nt, new_cache._replace(length=new_len)), nt

            (last, cache), toks = jax.lax.scan(body, (tokens, cache), keys)
            return toks, last, cache, rng

        M = self.admit_cap

        def _insert_many(slot_cache, new_cache, slot_idx, rows):
            """Copy new_cache row rows[i] into slot slot_idx[i] for i < M.
            Padding entries duplicate entry 0 (idempotent rewrite)."""

            def body(c, xs):
                si, row = xs
                k = jax.lax.dynamic_update_slice(
                    c.k,
                    jax.lax.dynamic_slice_in_dim(new_cache.k, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                v = jax.lax.dynamic_update_slice(
                    c.v,
                    jax.lax.dynamic_slice_in_dim(new_cache.v, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                length = jax.lax.dynamic_update_slice(
                    c.length,
                    jax.lax.dynamic_slice_in_dim(new_cache.length, row, 1, axis=0),
                    (si,),
                )
                return c._replace(k=k, v=v, length=length), None

            cache, _ = jax.lax.scan(body, slot_cache, (slot_idx, rows))
            return cache

        self._prefill_op = jax.jit(_prefill_op)
        self._chunk_op = jax.jit(_chunk_op, donate_argnums=(2,))
        self._insert_many = jax.jit(_insert_many, donate_argnums=(0,))
        self._rng = jax.random.PRNGKey(0)

        self.cache = init_cache(cfg, slots, max_seq_len)
        self._slot_req: list[GenRequest | None] = [None] * slots
        self._last_tok = np.zeros((slots,), np.int32)
        self._temps = np.zeros((slots,), np.float32)
        self._admit_q: queue.Queue[GenRequest | None] = queue.Queue()
        self._stop = False
        # in-flight decode chunks: deque of device [K, S] token arrays,
        # oldest first; _tail is the newest chunk's on-device last-token
        # vector (input for a chained speculative dispatch)
        self._inflight: deque = deque()
        self._tail = None
        self._jnp = jnp
        self._jax = jax

        if warmup:
            self._warm()
        self._thread = threading.Thread(target=self._loop, name="llm-engine", daemon=True)
        self._thread.start()

    # -- public API -------------------------------------------------------
    def submit(self, req: GenRequest) -> GenRequest:
        if self._stop:
            raise RuntimeError("engine stopped")
        if len(req.prompt_tokens) >= self.max_seq_len:
            raise ValueError(
                f"prompt of {len(req.prompt_tokens)} tokens exceeds max_seq_len {self.max_seq_len}"
            )
        self._admit_q.put(req)
        return req

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "active": sum(r is not None for r in self._slot_req),
            "waiting": self._admit_q.qsize(),
            "max_seq_len": self.max_seq_len,
            "decode_chunk": self.decode_chunk,
            "inflight_chunks": len(self._inflight),
        }

    def close(self) -> None:
        self._stop = True
        self._admit_q.put(None)
        self._thread.join(timeout=10)

    # -- engine internals -------------------------------------------------
    def _warm(self) -> None:
        jnp = self._jnp
        t0 = time.perf_counter()
        zero_rng = self._rng
        for b in self.prefill_buckets:
            toks = jnp.zeros((1, b), jnp.int32)
            lens = jnp.ones((1,), jnp.int32)
            temps = jnp.zeros((1,), jnp.float32)
            first, c, _ = self._prefill_op(self.params, toks, lens, temps, zero_rng)
            idx = jnp.zeros((self.admit_cap,), jnp.int32)
            self.cache = self._insert_many(self.cache, c, idx, idx)
        toks, last, self.cache, _ = self._chunk_op(
            self.params,
            jnp.zeros((self.slots,), jnp.int32),
            self.cache,
            jnp.zeros((self.slots,), bool),
            jnp.zeros((self.slots,), jnp.float32),
            zero_rng,
        )
        _ = np.asarray(last)  # sync (block_until_ready is unreliable on axon)
        self.cache = self.cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        if self.logger is not None:
            self.logger.info(
                f"LLM engine warmed in {time.perf_counter() - t0:.1f}s "
                f"(buckets {self.prefill_buckets}, slots {self.slots}, "
                f"chunk {self.decode_chunk})"
            )

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.max_seq_len

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _any_active(self) -> bool:
        return any(r is not None for r in self._slot_req)

    def _admit(self) -> bool:
        """Pull waiting requests into free slots, prefilling per bucket.
        Drains in-flight chunks first so the next dispatch starts from a
        host-merged last-token vector."""
        jnp = self._jnp
        free = self._free_slots()
        pulled: list[GenRequest] = []
        while len(pulled) < len(free):
            try:
                # Block briefly only when fully idle; stay hot otherwise.
                idle = not self._any_active() and not self._inflight and not pulled
                req = self._admit_q.get(timeout=0.05) if idle else self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is None:
                self._stop = True
                break
            if req.cancelled:
                req.out.put(None)
                continue
            pulled.append(req)
        if not pulled:
            return False
        self._flush()  # retire-complete + host-known last tokens
        free = self._free_slots()
        # group by bucket to share prefill executions; chunks of admit_cap
        by_bucket: dict[int, list[GenRequest]] = {}
        for r in pulled:
            by_bucket.setdefault(self._bucket_for(len(r.prompt_tokens)), []).append(r)
        by_wave: list[tuple[int, list[GenRequest]]] = []
        for bucket, reqs in by_bucket.items():
            for i in range(0, len(reqs), self.admit_cap):
                by_wave.append((bucket, reqs[i : i + self.admit_cap]))
        for bucket, reqs in by_wave:
            # batch dim: 1 for lone requests, admit_cap otherwise — two
            # executables per bucket, never a per-burst compile
            nb = 1 if len(reqs) == 1 else self.admit_cap
            toks = np.zeros((nb, bucket), np.int32)
            lens = np.ones((nb,), np.int32)  # pad rows: 1 token, discarded
            temps = np.zeros((nb,), np.float32)
            for j, r in enumerate(reqs):
                n = len(r.prompt_tokens)
                toks[j, :n] = r.prompt_tokens
                lens[j] = n
                temps[j] = r.temperature
            t0 = time.perf_counter()
            first_dev, new_cache, self._rng = self._prefill_op(
                self.params, jnp.asarray(toks), jnp.asarray(lens),
                jnp.asarray(temps), self._rng,
            )
            first = np.asarray(first_dev)
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_tpu_stats", time.perf_counter() - t0,
                    model="llm", op=f"prefill_{bucket}",
                )
            slot_idx = np.zeros((self.admit_cap,), np.int32)
            rows = np.zeros((self.admit_cap,), np.int32)
            taken: list[int] = []
            for j, r in enumerate(reqs):
                slot = free.pop(0)
                taken.append(slot)
                self._slot_req[slot] = r
                self._last_tok[slot] = first[j]
                self._temps[slot] = r.temperature
                slot_idx[j], rows[j] = slot, j
            # pad entries duplicate entry 0 (idempotent)
            for j in range(len(reqs), self.admit_cap):
                slot_idx[j], rows[j] = slot_idx[0], rows[0]
            self.cache = self._insert_many(
                self.cache, new_cache, jnp.asarray(slot_idx), jnp.asarray(rows)
            )
            for j, slot in enumerate(taken):
                self._emit(slot, int(first[j]))
        return True

    def _emit(self, slot: int, token: int) -> None:
        r = self._slot_req[slot]
        if r is None:
            return
        if r.cancelled:
            self._retire(slot)
            return
        r.out.put(token)
        r.emitted += 1
        if token == r.eos_token or r.emitted >= r.max_new_tokens:
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        r = self._slot_req[slot]
        if r is not None:
            r.out.put(None)
        self._slot_req[slot] = None
        self._temps[slot] = 0.0

    def _dispatch(self) -> None:
        """Launch one decode chunk. The first chunk of a chain starts from
        the host-merged token vector; subsequent chunks chain from the
        previous chunk's on-device output, so the device never stalls on
        host readback."""
        jnp = self._jnp
        src = self._tail if self._tail is not None else jnp.asarray(self._last_tok)
        active = np.array([r is not None for r in self._slot_req])
        toks, last, self.cache, self._rng = self._chunk_op(
            self.params, src, self.cache,
            jnp.asarray(active), jnp.asarray(self._temps), self._rng,
        )
        self._tail = last
        self._inflight.append(toks)

    def _process_one(self) -> None:
        """Read back the oldest in-flight chunk and emit its tokens."""
        toks_dev = self._inflight.popleft()
        t0 = time.perf_counter()
        toks = np.asarray(toks_dev)  # [K, S] — blocks; device runs next chunk
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_stats", time.perf_counter() - t0,
                model="llm", op="decode_chunk",
            )
        for k in range(toks.shape[0]):
            for slot in range(self.slots):
                r = self._slot_req[slot]
                if r is None:
                    continue
                if r.emitted + len(r.prompt_tokens) >= self.max_seq_len - 1:
                    self._retire(slot)  # cache capacity guard
                    continue
                self._emit(slot, int(toks[k, slot]))
        self._last_tok = toks[-1].copy()
        if not self._inflight:
            self._tail = None

    def _flush(self) -> None:
        while self._inflight:
            self._process_one()
        self._tail = None

    def _loop(self) -> None:
        while not self._stop:
            try:
                self._admit()
                if self._stop:
                    break
                if self._any_active():
                    if not self._inflight:
                        self._dispatch()
                    # speculative chunk: only when no admission is possible
                    # (otherwise the next loop iteration admits instead)
                    can_admit = self._admit_q.qsize() > 0 and self._free_slots()
                    while len(self._inflight) < self.lookahead and not can_admit:
                        self._dispatch()
                if self._inflight:
                    self._process_one()
            except Exception as e:  # noqa: BLE001 — engine must not die silently
                if self.logger is not None:
                    self.logger.error(f"LLM engine step failed: {e!r}")
                self._inflight.clear()
                self._tail = None
                for slot in range(self.slots):
                    self._retire(slot)
                time.sleep(0.1)
        # drain
        self._flush()
        for slot in range(self.slots):
            self._retire(slot)
        while True:
            try:
                req = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.out.put(None)
