"""LLM serving engine: slot-based continuous batching with token streaming.

The decode-serving core for BASELINE.json configs 3/5 (gRPC streaming
Gemma decode; multi-chip tensor-parallel serving). No counterpart in the
reference repo — this is the TPU-native replacement for its goroutine-per-
request model at the model-serving layer (SURVEY.md §7 hard part 5:
"continuous batching / slot-based scheduler is the real design problem").

Design (all shapes static; a bounded set of compiled executables):

- **Slots.** A fixed decode batch of S slots with one persistent KV cache
  [n_layers, S, max_seq_len, hkv, hd] on device. Inactive slots are masked
  (their tokens are discarded on host; their cursors never advance).
- **Fused decode chunks.** Decode advances ALL slots K steps per dispatch
  (models.transformer.decode_chunk: a lax.scan over a chunk-ring-buffer
  layer body with on-device sampling — the main cache is read-only inside
  a chunk and merged once at chunk end, so no per-step scatter). One
  host→device dispatch per K tokens amortizes dispatch latency, and the
  engine keeps up to `lookahead` chunks in flight, chaining each chunk's
  input tokens from the previous chunk's on-device output so the device
  never waits for host readback.
- **Admission without stalling decode.** Prefill waves dispatch
  asynchronously BETWEEN decode chunks; the first sampled token is merged
  into the on-device tail vector by a jitted scatter (no host round trip),
  and prefilled KV rows are copied into free slots via ONE jitted
  insert-many. Decode chunks already in flight keep streaming — their
  tokens for a reused slot are dropped on host via per-slot generation
  tags, never by draining the pipeline (the r2 engine's flush-before-admit
  barrier cost 72% of raw decode throughput).
- **On-device sampling.** Greedy or temperature sampling happens inside the
  chunk; the host syncs one [K, S] int32 array per chunk (started with
  copy_to_host_async at dispatch) instead of logits.
- **Streaming.** Each request owns a thread-safe queue; the engine thread
  pushes per-chunk token LISTS as fetches complete; consumers iterate
  stream() (sync) or astream() (async) and detach by cancelling — a
  detached request just frees its slot, never stalling the batch.

Tensor parallelism: pass mesh + param_specs; the slot cache is resharded by
GSPMD from the params' shardings (KV replicated under MQA, sharded when the
TP degree divides n_kv_heads) — identical code single-chip and multi-chip.
Quantization: quantize=True serves int8 weights (models.quant), halving the
HBM stream that bounds decode.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np

__all__ = ["LLMEngine", "GenRequest"]

_EOS_DEFAULT = -1  # no EOS cut by default (random-weight models)


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    max_new_tokens: int = 32
    temperature: float = 0.0
    eos_token: int = _EOS_DEFAULT
    id: int = field(default_factory=itertools.count().__next__)

    def __post_init__(self):
        self.out: queue.Queue = queue.Queue()
        self.cancelled = False
        self.emitted = 0
        self.capped = False  # engine reduced max_new_tokens to fit the cache
        self.finish_reason: str | None = None  # "eos" | "length" | "cancelled"
        self.submitted_at: float | None = None

    # -- consumption ------------------------------------------------------
    def stream(self, timeout: float = 60.0) -> Iterator[int]:
        """Yield token ids until the engine signals completion."""
        while True:
            item = self.out.get(timeout=timeout)
            if item is None:
                return
            if isinstance(item, list):
                yield from item
            else:
                yield item

    async def astream(self, timeout: float = 60.0):
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            item = await loop.run_in_executor(None, lambda: self.out.get(timeout=timeout))
            if item is None:
                return
            if isinstance(item, list):
                for t in item:
                    yield t
            else:
                yield item

    def cancel(self) -> None:
        self.cancelled = True

    def tokens(self, timeout: float = 60.0) -> list[int]:
        return list(self.stream(timeout=timeout))


class LLMEngine:
    def __init__(
        self,
        cfg,
        params,
        *,
        slots: int = 32,
        max_seq_len: int = 512,
        prefill_buckets: tuple[int, ...] = (16, 64, 128),
        decode_chunk: int = 8,
        lookahead: int = 3,
        admit_cap: int = 8,
        mesh=None,
        param_specs: Any = None,
        logger=None,
        metrics=None,
        warmup: bool = True,
        quantize: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from .models.transformer import decode_chunk as chunk_fn
        from .models.transformer import init_cache, prefill

        if quantize:
            from .models.quant import quantize_param_specs, quantize_params

            # int8 weights halve the HBM stream decode is bound by
            # (VERDICT r2: 5.0 GB bf16 -> 2.5 GB); no-op if already quantized.
            params = jax.jit(lambda p: quantize_params(p, cfg.dtype))(params)
            if param_specs is not None:
                param_specs = quantize_param_specs(param_specs)
        self.quantized = quantize

        self.cfg = cfg
        self.slots = slots
        self.max_seq_len = max_seq_len
        self.prefill_buckets = tuple(sorted(b for b in prefill_buckets if b <= max_seq_len))
        self.decode_chunk = decode_chunk
        self.lookahead = max(1, lookahead)
        self.admit_cap = min(admit_cap, slots)
        self.logger = logger
        self.metrics = metrics
        if mesh is not None and param_specs is not None:
            from .parallel.sharding import shard_params

            params = shard_params(params, mesh, param_specs)
        else:
            params = jax.device_put(params)
        self.params = params

        # -- jitted programs (one dispatch each) --------------------------
        topk = min(64, cfg.vocab_size)

        def _sample(logits, temps, key):
            """Greedy for temp==0; temperature sampling restricted to the
            top-k logits otherwise. Full-vocab categorical would generate
            batch x vocab Gumbel draws per step (millions of threefry
            rounds for a 256k vocab) and dominates decode time; top-k keeps
            the RNG work at batch x 64."""
            greedy = jnp.argmax(logits, axis=-1)
            topv, topi = jax.lax.approx_max_k(logits, topk)
            local = jax.random.categorical(
                key, topv / jnp.maximum(temps, 1e-4)[:, None], axis=-1
            )
            sampled = jnp.take_along_axis(topi, local[:, None], axis=1)[:, 0]
            return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)

        def _prefill_op(params, tokens, lengths, temps, rng):
            last_logits, cache = prefill(params, cfg, tokens, lengths, max_seq_len)
            rng, sub = jax.random.split(rng)
            first = _sample(last_logits, temps, sub)
            return first, cache, rng

        K = decode_chunk

        def _chunk_op(params, tokens, cache, active, temps, rng):
            return chunk_fn(
                params, cfg, tokens, cache, active, temps, rng,
                n_steps=K, sample_fn=_sample,
            )

        M = self.admit_cap

        def _insert_many(slot_cache, new_cache, slot_idx, rows):
            """Copy new_cache row rows[i] into slot slot_idx[i] for i < M.
            Padding entries duplicate entry 0 (idempotent rewrite)."""

            def body(c, xs):
                si, row = xs
                k = jax.lax.dynamic_update_slice(
                    c.k,
                    jax.lax.dynamic_slice_in_dim(new_cache.k, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                v = jax.lax.dynamic_update_slice(
                    c.v,
                    jax.lax.dynamic_slice_in_dim(new_cache.v, row, 1, axis=1),
                    (0, si, 0, 0, 0),
                )
                length = jax.lax.dynamic_update_slice(
                    c.length,
                    jax.lax.dynamic_slice_in_dim(new_cache.length, row, 1, axis=0),
                    (si,),
                )
                return c._replace(k=k, v=v, length=length), None

            cache, _ = jax.lax.scan(body, slot_cache, (slot_idx, rows))
            return cache

        def _merge_tail(tail, slot_idx, rows, first):
            """Scatter freshly-prefilled first tokens into the on-device
            chain tail — admission never forces a host round trip. Padding
            entries repeat slot_idx[0]/rows[0] (idempotent)."""
            return tail.at[slot_idx].set(first[rows])

        self._prefill_op = jax.jit(_prefill_op)
        self._chunk_op = jax.jit(_chunk_op, donate_argnums=(2,))
        self._insert_many = jax.jit(_insert_many, donate_argnums=(0,))
        self._merge_tail = jax.jit(_merge_tail, donate_argnums=(0,))
        self._rng = jax.random.PRNGKey(0)

        self.cache = init_cache(cfg, slots, max_seq_len)
        self._slot_req: list[GenRequest | None] = [None] * slots
        self._gen = np.zeros((slots,), np.int64)  # per-slot assignment epoch
        self._temps = np.zeros((slots,), np.float32)
        self._tail = jnp.zeros((slots,), jnp.int32)  # device: next chunk input
        self._admit_q: queue.Queue[GenRequest | None] = queue.Queue()
        self._stop = False
        # in-flight device work, oldest first:
        #   ("chunk", toks_dev [K,S], gens snapshot)
        #   ("prefill", first_dev [nb], slots list, gens list)
        self._inflight: deque = deque()
        self._jnp = jnp
        self._jax = jax

        if warmup:
            self._warm()
        self._thread = threading.Thread(target=self._loop, name="llm-engine", daemon=True)
        self._thread.start()

    # -- public API -------------------------------------------------------
    def submit(self, req: GenRequest) -> GenRequest:
        if self._stop:
            raise RuntimeError("engine stopped")
        plen = len(req.prompt_tokens)
        if plen >= self.max_seq_len:
            raise ValueError(
                f"prompt of {plen} tokens exceeds max_seq_len {self.max_seq_len}"
            )
        # Cap max_new_tokens so the slot's cursor can never clamp-overwrite
        # its own live rows: while a request is incomplete its length stays
        # <= prompt + max_new + chunk (chunk-granularity rounding), and the
        # end-of-chunk merge needs a further chunk of slack. A request that
        # cannot emit a single token is rejected outright.
        room = self.max_seq_len - plen - 2 * self.decode_chunk
        if room < 1:
            raise ValueError(
                f"prompt of {plen} tokens leaves no decode room at "
                f"max_seq_len {self.max_seq_len} (chunk {self.decode_chunk})"
            )
        if req.max_new_tokens > room:
            req.max_new_tokens = room
            req.capped = True
        req.submitted_at = time.perf_counter()
        self._admit_q.put(req)
        return req

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def stats(self) -> dict:
        return {
            "slots": self.slots,
            "active": sum(r is not None for r in self._slot_req),
            "waiting": self._admit_q.qsize(),
            "max_seq_len": self.max_seq_len,
            "decode_chunk": self.decode_chunk,
            "inflight_chunks": sum(1 for e in self._inflight if e[0] == "chunk"),
        }

    def close(self) -> None:
        self._stop = True
        self._admit_q.put(None)
        self._thread.join(timeout=10)

    # -- engine internals -------------------------------------------------
    def _warm(self) -> None:
        jnp = self._jnp
        t0 = time.perf_counter()
        zero_rng = self._rng
        idx = jnp.zeros((self.admit_cap,), jnp.int32)
        for b in self.prefill_buckets:
            for nb in dict.fromkeys((1, self.admit_cap)):
                toks = jnp.zeros((nb, b), jnp.int32)
                lens = jnp.ones((nb,), jnp.int32)
                temps = jnp.zeros((nb,), jnp.float32)
                first, c, _ = self._prefill_op(self.params, toks, lens, temps, zero_rng)
                self.cache = self._insert_many(self.cache, c, idx, idx % nb)
                self._tail = self._merge_tail(self._tail, idx, idx % nb, first)
        toks, last, self.cache, _ = self._chunk_op(
            self.params,
            jnp.zeros((self.slots,), jnp.int32),
            self.cache,
            jnp.zeros((self.slots,), bool),
            jnp.zeros((self.slots,), jnp.float32),
            zero_rng,
        )
        _ = np.asarray(last)  # sync (block_until_ready is unreliable on axon)
        self.cache = self.cache._replace(length=jnp.zeros((self.slots,), jnp.int32))
        self._tail = jnp.zeros((self.slots,), jnp.int32)
        if self.logger is not None:
            self.logger.info(
                f"LLM engine warmed in {time.perf_counter() - t0:.1f}s "
                f"(buckets {self.prefill_buckets}, slots {self.slots}, "
                f"chunk {self.decode_chunk})"
            )

    def _bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.max_seq_len

    def _free_slots(self) -> list[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _any_active(self) -> bool:
        return any(r is not None for r in self._slot_req)

    def _admit(self) -> bool:
        """Pull waiting requests into free slots, prefilling per bucket.
        Purely dispatch-side: decode chunks in flight are untouched (their
        tokens for reused slots are dropped by generation tag), and the
        first sampled tokens merge into the device tail without a host
        round trip."""
        jnp = self._jnp
        free = self._free_slots()
        pulled: list[GenRequest] = []
        while len(pulled) < len(free):
            try:
                # Block briefly only when fully idle; stay hot otherwise.
                idle = not self._any_active() and not self._inflight and not pulled
                req = self._admit_q.get(timeout=0.05) if idle else self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is None:
                self._stop = True
                break
            if req.cancelled:
                req.finish_reason = "cancelled"
                req.out.put(None)
                continue
            pulled.append(req)
        if not pulled:
            return False
        # group by bucket to share prefill executions; chunks of admit_cap
        by_bucket: dict[int, list[GenRequest]] = {}
        for r in pulled:
            by_bucket.setdefault(self._bucket_for(len(r.prompt_tokens)), []).append(r)
        by_wave: list[tuple[int, list[GenRequest]]] = []
        for bucket, reqs in by_bucket.items():
            for i in range(0, len(reqs), self.admit_cap):
                by_wave.append((bucket, reqs[i : i + self.admit_cap]))
        for bucket, reqs in by_wave:
            # batch dim: 1 for lone requests, admit_cap otherwise — two
            # executables per bucket, never a per-burst compile
            nb = 1 if len(reqs) == 1 else self.admit_cap
            toks = np.zeros((nb, bucket), np.int32)
            lens = np.ones((nb,), np.int32)  # pad rows: 1 token, discarded
            temps = np.zeros((nb,), np.float32)
            for j, r in enumerate(reqs):
                n = len(r.prompt_tokens)
                toks[j, :n] = r.prompt_tokens
                lens[j] = n
                temps[j] = r.temperature
            t0 = time.perf_counter()
            first_dev, new_cache, self._rng = self._prefill_op(
                self.params, jnp.asarray(toks), jnp.asarray(lens),
                jnp.asarray(temps), self._rng,
            )
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_tpu_stats", time.perf_counter() - t0,
                    model="llm", op=f"prefill_dispatch_{bucket}",
                )
            free = self._free_slots()
            slot_idx = np.zeros((self.admit_cap,), np.int32)
            rows = np.zeros((self.admit_cap,), np.int32)
            taken: list[int] = []
            for j, r in enumerate(reqs):
                slot = free.pop(0)
                taken.append(slot)
                self._slot_req[slot] = r
                self._gen[slot] += 1
                self._temps[slot] = r.temperature
                slot_idx[j], rows[j] = slot, j
            # pad entries duplicate entry 0 (idempotent)
            for j in range(len(reqs), self.admit_cap):
                slot_idx[j], rows[j] = slot_idx[0], rows[0]
            self.cache = self._insert_many(
                self.cache, new_cache, jnp.asarray(slot_idx), jnp.asarray(rows)
            )
            self._tail = self._merge_tail(
                self._tail, jnp.asarray(slot_idx), jnp.asarray(rows), first_dev
            )
            self._start_fetch(first_dev)
            self._inflight.append(
                ("prefill", first_dev, list(taken), [self._gen[s] for s in taken])
            )
        return True

    @staticmethod
    def _start_fetch(arr) -> None:
        copy = getattr(arr, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # pragma: no cover — backend-dependent
                pass

    def _emit_tokens(self, slot: int, toks: list[int]) -> None:
        """Append a request's next tokens, honoring max_new/eos/cancel."""
        r = self._slot_req[slot]
        if r is None:
            return
        if r.cancelled:
            r.finish_reason = "cancelled"
            self._retire(slot)
            return
        take = min(len(toks), r.max_new_tokens - r.emitted)
        toks = toks[:take]
        finish = None
        if r.eos_token >= 0 and r.eos_token in toks:
            toks = toks[: toks.index(r.eos_token) + 1]
            finish = "eos"
        if r.emitted == 0 and r.submitted_at is not None and self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_queue_wait", time.perf_counter() - r.submitted_at,
                model="llm", op="ttft",
            )
        if toks:
            r.out.put(toks)
            r.emitted += len(toks)
        if finish is None and r.emitted >= r.max_new_tokens:
            finish = "length"
        if finish is not None:
            r.finish_reason = finish
            self._retire(slot)

    def _retire(self, slot: int) -> None:
        r = self._slot_req[slot]
        if r is not None:
            r.out.put(None)
        self._slot_req[slot] = None
        self._gen[slot] += 1
        self._temps[slot] = 0.0

    def _dispatch(self) -> None:
        """Launch one decode chunk chained from the on-device tail."""
        jnp = self._jnp
        active = np.array([r is not None for r in self._slot_req])
        toks, last, self.cache, self._rng = self._chunk_op(
            self.params, self._tail, self.cache,
            jnp.asarray(active), jnp.asarray(self._temps), self._rng,
        )
        self._tail = last
        self._start_fetch(toks)
        self._inflight.append(("chunk", toks, self._gen.copy()))

    def _process_one(self) -> None:
        """Read back the oldest in-flight device result and emit tokens."""
        entry = self._inflight.popleft()
        if entry[0] == "prefill":
            _, first_dev, slots_, gens = entry
            first = np.asarray(first_dev)
            for j, slot in enumerate(slots_):
                if self._gen[slot] == gens[j]:
                    self._emit_tokens(slot, [int(first[j])])
            return
        _, toks_dev, gens = entry
        t0 = time.perf_counter()
        toks = np.asarray(toks_dev)  # [K, S] — blocks; device runs next chunk
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_stats", time.perf_counter() - t0,
                model="llm", op="decode_chunk",
            )
        cols = toks.T  # [S, K]
        for slot in range(self.slots):
            if self._slot_req[slot] is None or self._gen[slot] != gens[slot]:
                continue
            self._emit_tokens(slot, cols[slot].tolist())

    def _flush(self) -> None:
        while self._inflight:
            self._process_one()

    def _loop(self) -> None:
        jnp = self._jnp
        while not self._stop:
            try:
                self._admit()
                if self._stop:
                    break
                if self._any_active():
                    depth = sum(1 for e in self._inflight if e[0] == "chunk")
                    while depth < self.lookahead:
                        self._dispatch()
                        depth += 1
                if self._inflight:
                    self._process_one()
            except Exception as e:  # noqa: BLE001 — engine must not die silently
                if self.logger is not None:
                    self.logger.error(f"LLM engine step failed: {e!r}")
                self._inflight.clear()
                self._tail = jnp.zeros((self.slots,), jnp.int32)
                for slot in range(self.slots):
                    self._retire(slot)
                time.sleep(0.1)
        # drain
        self._flush()
        for slot in range(self.slots):
            self._retire(slot)
        while True:
            try:
                req = self._admit_q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.out.put(None)
