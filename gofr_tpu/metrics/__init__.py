"""Metrics: manager + instruments + Prometheus text exposition.

Parity: reference pkg/gofr/metrics/ — Manager interface with
new/increment Counter, UpDownCounter, Histogram, Gauge (register.go:15-25),
name->instrument store (store.go:7-34), synchronous gauge (register.go:40-46),
label validation warnings, Prometheus exporter (exporters/exporter.go:14-29).

Implementation is self-contained (no OTel SDK in the hot path): instruments
are lock-light — counters/gauges use a per-instrument dict guarded by a small
lock; histograms pre-compute bucket bounds. The serving hot loop records two
histograms per request (http + tpu), same budget as the reference
(SURVEY.md §3.3).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Iterable

from ..logging import Logger

DEFAULT_HISTOGRAM_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
)
# Reference container.go:176: .001 - 30s for HTTP response histograms.
HTTP_BUCKETS = (0.001, 0.003, 0.005, 0.01, 0.02, 0.03, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1, 2, 3, 5, 10, 30)
# Reference container.go:182-188: sub-ms buckets for datasource ops.
DATASOURCE_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.002, 0.003, 0.005, 0.0075, 0.01)
# TPU execute latencies: 100us .. 5s (first decode steps / big batches).
TPU_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, description: str):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._series: dict[tuple[tuple[str, str], ...], float] = {}

    def _bump(self, delta: float, labels: dict[str, str]) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + delta

    def _set(self, value: float, labels: dict[str, str]) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = value

    def collect(self) -> Iterable[tuple[str, dict[str, str], float]]:
        with self._lock:
            items = list(self._series.items())
        for key, value in items:
            yield self.name, dict(key), value


class Counter(_Instrument):
    kind = "counter"

    def increment(self, by: float = 1.0, **labels: str) -> None:
        self._bump(by, labels)


class UpDownCounter(_Instrument):
    kind = "gauge"  # prometheus has no native updown; exposed as gauge

    def delta(self, by: float, **labels: str) -> None:
        self._bump(by, labels)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        self._set(value, labels)


class Histogram:
    kind = "histogram"

    def __init__(self, name: str, description: str, buckets: tuple[float, ...]):
        self.name = name
        self.description = description
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # per label-set: [bucket counts..., +inf count], sum, count
        self._series: dict[tuple[tuple[str, str], ...], list] = {}
        # per label-set: bucket index -> (exemplar labels, value, unix ts) —
        # last-wins, bounded by (label sets x buckets), so a percentile on
        # the exposition always links the most recent trace that landed in
        # that bucket (OpenMetrics exemplars).
        self._exemplars: dict[tuple[tuple[str, str], ...], dict[int, tuple]] = {}

    def record(self, value: float, exemplar: dict | None = None, **labels: str) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = s
            s[0][idx] += 1
            s[1] += value
            s[2] += 1
            if exemplar:
                self._exemplars.setdefault(key, {})[idx] = (
                    dict(exemplar), value, time.time(),
                )

    def collect_histogram(self):
        with self._lock:
            items = [(k, ([*v[0]], v[1], v[2])) for k, v in self._series.items()]
        return items

    def collect_exemplars(self):
        with self._lock:
            return {k: dict(v) for k, v in self._exemplars.items()}

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket midpoints (for health/bench)."""
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if not s:
                return 0.0
            counts, _, total = [*s[0]], s[1], s[2]
        if total == 0:
            return 0.0
        target = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                if i < len(self.buckets):
                    return self.buckets[i]
                return self.buckets[-1]
        return self.buckets[-1]


def summarize_window(values: Iterable[float]) -> dict:
    """{count, p50, p99, max} over a sample list — exact order statistics,
    unlike Histogram.percentile's bucket-midpoint approximation. Used for
    the serving engine's recent-window phase summaries (stats()/debug)."""
    xs = sorted(values)
    if not xs:
        return {"count": 0, "p50": 0.0, "p99": 0.0, "max": 0.0}
    return {
        "count": len(xs),
        "p50": xs[len(xs) // 2],
        "p99": xs[min(len(xs) - 1, int(0.99 * len(xs)))],
        "max": xs[-1],
    }


class RollingWindow:
    """Fixed-size window of recent observations with exact percentiles.

    The Prometheus histograms are cumulative-forever; live debugging wants
    "what do the LAST few hundred requests look like" — this keeps that
    window in-process at deque-append cost (O(1), one small lock) so the
    serving hot loop can afford one observe() per phase transition.

    With `max_age_s` set the window is additionally time-bounded: each
    observation is timestamped and values older than the horizon fall out
    on read — the form the SLO burn-rate engine uses for its 5m/1h
    goodness windows (a quiet tenant's hour-old failures must stop
    burning budget once they age past the window).
    """

    def __init__(self, size: int = 512, max_age_s: float | None = None, clock=None):
        from collections import deque

        self._lock = threading.Lock()
        self._age = float(max_age_s) if max_age_s else None
        self._clock = clock if clock is not None else time.monotonic
        self._values: deque = deque(maxlen=size)
        self._sum = 0.0  # running sum -> O(1) mean() on the SLO hot path

    def observe(self, value: float) -> None:
        with self._lock:
            if (
                self._values.maxlen is not None
                and len(self._values) == self._values.maxlen
                and self._values
            ):
                evicted = self._values[0]
                self._sum -= evicted[1] if self._age is not None else evicted
            if self._age is None:
                self._values.append(value)
            else:
                self._values.append((self._clock(), value))
            self._sum += value

    def _trim_locked(self) -> None:
        if self._age is None:
            return
        horizon = self._clock() - self._age
        while self._values and self._values[0][0] < horizon:
            _, v = self._values.popleft()
            self._sum -= v

    def values(self) -> list[float]:
        with self._lock:
            self._trim_locked()
            if self._age is None:
                return list(self._values)
            return [v for _, v in self._values]

    def mean(self) -> float:
        with self._lock:
            self._trim_locked()
            n = len(self._values)
            return (self._sum / n) if n else 0.0

    def __len__(self) -> int:
        with self._lock:
            self._trim_locked()
            return len(self._values)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()
            self._sum = 0.0

    def summary(self) -> dict:
        return summarize_window(self.values())


class Manager:
    """Name->instrument registry. Parity: metrics/register.go + store.go."""

    def __init__(self, logger: Logger | None = None):
        self._logger = logger
        self._lock = threading.Lock()
        self._instruments: dict[str, object] = {}

    def _register(self, name: str, inst):
        with self._lock:
            if name in self._instruments:
                if self._logger:
                    self._logger.warn(f"metric {name} already registered")
                return self._instruments[name]
            self._instruments[name] = inst
            return inst

    def has(self, name: str) -> bool:
        """Silent existence check — for idempotent framework registration
        paths (the WARN in _register is for USER double registration, the
        ERROR in _get for using an unregistered metric)."""
        with self._lock:
            return name in self._instruments

    def new_counter(self, name: str, description: str = "") -> Counter:
        return self._register(name, Counter(name, description))

    def new_updown_counter(self, name: str, description: str = "") -> UpDownCounter:
        return self._register(name, UpDownCounter(name, description))

    def new_gauge(self, name: str, description: str = "") -> Gauge:
        return self._register(name, Gauge(name, description))

    def new_histogram(
        self, name: str, description: str = "", buckets: tuple[float, ...] = DEFAULT_HISTOGRAM_BUCKETS
    ) -> Histogram:
        return self._register(name, Histogram(name, description, buckets))

    def _get(self, name: str, kind):
        inst = self._instruments.get(name)
        if inst is None or not isinstance(inst, kind):
            if self._logger:
                self._logger.error(f"metric {name} not registered as {kind.__name__}")
            return None
        return inst

    # Verb API mirroring the reference Manager (register.go:15-25): callers
    # address instruments by name so user code never holds instrument objects.
    def increment_counter(self, name: str, by: float = 1.0, **labels: str) -> None:
        c = self._get(name, Counter)
        if c:
            c.increment(by, **labels)

    def delta_updown_counter(self, name: str, by: float, **labels: str) -> None:
        c = self._get(name, UpDownCounter)
        if c:
            c.delta(by, **labels)

    def record_histogram(self, name: str, value: float, exemplar: dict | None = None, **labels: str) -> None:
        h = self._get(name, Histogram)
        if h:
            h.record(value, exemplar=exemplar, **labels)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        g = self._get(name, Gauge)
        if g:
            g.set(value, **labels)

    def histogram(self, name: str) -> Histogram | None:
        return self._get(name, Histogram)

    def gauge_total(self, name: str) -> float:
        """Sum of a gauge across its label sets (0.0 when unregistered).
        Silent like has(): framework health probes read engine gauges
        that only exist once an LLM is registered."""
        with self._lock:
            g = self._instruments.get(name)
        if not isinstance(g, Gauge):
            return 0.0
        return sum(value for _name, _labels, value in g.collect())

    # -- exposition --
    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4."""
        return self._render(openmetrics=False)

    def render_openmetrics(self) -> str:
        """OpenMetrics text: the 0.0.4 exposition plus histogram-bucket
        exemplars (`... # {trace_id="..."} value timestamp`) and the
        mandatory `# EOF` terminator. Exemplars are only legal on this
        content type, so the metrics server negotiates it via Accept —
        it is how a p99 bucket links back to a stitchable journey."""
        return self._render(openmetrics=True)

    def _render(self, openmetrics: bool) -> str:
        with self._lock:
            instruments = list(self._instruments.values())
        out: list[str] = []
        for inst in instruments:
            name = inst.name  # type: ignore[attr-defined]
            if inst.description:  # type: ignore[attr-defined]
                out.append(f"# HELP {name} {inst.description}")  # type: ignore[attr-defined]
            out.append(f"# TYPE {name} {inst.kind}")  # type: ignore[attr-defined]
            if isinstance(inst, Histogram):
                exemplars = inst.collect_exemplars() if openmetrics else {}
                for key, (counts, total_sum, count) in inst.collect_histogram():
                    base = dict(key)
                    ex = exemplars.get(key, {})
                    acc = 0
                    for i, (ub, c) in enumerate(zip(inst.buckets, counts)):
                        acc += c
                        line = _line(f"{name}_bucket", {**base, "le": _fmt(ub)}, acc)
                        if i in ex:
                            line += _exemplar_suffix(*ex[i])
                        out.append(line)
                    acc += counts[-1]
                    line = _line(f"{name}_bucket", {**base, "le": "+Inf"}, acc)
                    if len(inst.buckets) in ex:
                        line += _exemplar_suffix(*ex[len(inst.buckets)])
                    out.append(line)
                    out.append(_line(f"{name}_sum", base, total_sum))
                    out.append(_line(f"{name}_count", base, count))
            else:
                for mname, labels, value in inst.collect():  # type: ignore[attr-defined]
                    out.append(_line(mname, labels, value))
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


def _fmt(v: float) -> str:
    return f"{v:g}"


def _line(name: str, labels: dict[str, str], value) -> str:
    if labels:
        lab = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _exemplar_suffix(labels: dict, value: float, ts: float) -> str:
    lab = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
    return f" # {{{lab}}} {_fmt(float(value))} {ts:.3f}"


def new_metrics_manager(logger: Logger | None = None) -> Manager:
    return Manager(logger)
