"""Metrics: manager + instruments + Prometheus text exposition.

Parity: reference pkg/gofr/metrics/ — Manager interface with
new/increment Counter, UpDownCounter, Histogram, Gauge (register.go:15-25),
name->instrument store (store.go:7-34), synchronous gauge (register.go:40-46),
label validation warnings, Prometheus exporter (exporters/exporter.go:14-29).

Implementation is self-contained (no OTel SDK in the hot path): instruments
are lock-light — counters/gauges use a per-instrument dict guarded by a small
lock; histograms pre-compute bucket bounds. The serving hot loop records two
histograms per request (http + tpu), same budget as the reference
(SURVEY.md §3.3).
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterable

from ..logging import Logger

DEFAULT_HISTOGRAM_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
)
# Reference container.go:176: .001 - 30s for HTTP response histograms.
HTTP_BUCKETS = (0.001, 0.003, 0.005, 0.01, 0.02, 0.03, 0.05, 0.1, 0.2, 0.3, 0.5, 0.75, 1, 2, 3, 5, 10, 30)
# Reference container.go:182-188: sub-ms buckets for datasource ops.
DATASOURCE_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.002, 0.003, 0.005, 0.0075, 0.01)
# TPU execute latencies: 100us .. 5s (first decode steps / big batches).
TPU_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, description: str):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._series: dict[tuple[tuple[str, str], ...], float] = {}

    def _bump(self, delta: float, labels: dict[str, str]) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + delta

    def _set(self, value: float, labels: dict[str, str]) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = value

    def collect(self) -> Iterable[tuple[str, dict[str, str], float]]:
        with self._lock:
            items = list(self._series.items())
        for key, value in items:
            yield self.name, dict(key), value


class Counter(_Instrument):
    kind = "counter"

    def increment(self, by: float = 1.0, **labels: str) -> None:
        self._bump(by, labels)


class UpDownCounter(_Instrument):
    kind = "gauge"  # prometheus has no native updown; exposed as gauge

    def delta(self, by: float, **labels: str) -> None:
        self._bump(by, labels)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        self._set(value, labels)


class Histogram:
    kind = "histogram"

    def __init__(self, name: str, description: str, buckets: tuple[float, ...]):
        self.name = name
        self.description = description
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        # per label-set: [bucket counts..., +inf count], sum, count
        self._series: dict[tuple[tuple[str, str], ...], list] = {}

    def record(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = s
            s[0][idx] += 1
            s[1] += value
            s[2] += 1

    def collect_histogram(self):
        with self._lock:
            items = [(k, ([*v[0]], v[1], v[2])) for k, v in self._series.items()]
        return items

    def percentile(self, q: float, **labels: str) -> float:
        """Approximate percentile from bucket midpoints (for health/bench)."""
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if not s:
                return 0.0
            counts, _, total = [*s[0]], s[1], s[2]
        if total == 0:
            return 0.0
        target = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                if i < len(self.buckets):
                    return self.buckets[i]
                return self.buckets[-1]
        return self.buckets[-1]


def summarize_window(values: Iterable[float]) -> dict:
    """{count, p50, p99, max} over a sample list — exact order statistics,
    unlike Histogram.percentile's bucket-midpoint approximation. Used for
    the serving engine's recent-window phase summaries (stats()/debug)."""
    xs = sorted(values)
    if not xs:
        return {"count": 0, "p50": 0.0, "p99": 0.0, "max": 0.0}
    return {
        "count": len(xs),
        "p50": xs[len(xs) // 2],
        "p99": xs[min(len(xs) - 1, int(0.99 * len(xs)))],
        "max": xs[-1],
    }


class RollingWindow:
    """Fixed-size window of recent observations with exact percentiles.

    The Prometheus histograms are cumulative-forever; live debugging wants
    "what do the LAST few hundred requests look like" — this keeps that
    window in-process at deque-append cost (O(1), one small lock) so the
    serving hot loop can afford one observe() per phase transition."""

    def __init__(self, size: int = 512):
        from collections import deque

        self._lock = threading.Lock()
        self._values: deque[float] = deque(maxlen=size)

    def observe(self, value: float) -> None:
        with self._lock:
            self._values.append(value)

    def values(self) -> list[float]:
        with self._lock:
            return list(self._values)

    def summary(self) -> dict:
        return summarize_window(self.values())


class Manager:
    """Name->instrument registry. Parity: metrics/register.go + store.go."""

    def __init__(self, logger: Logger | None = None):
        self._logger = logger
        self._lock = threading.Lock()
        self._instruments: dict[str, object] = {}

    def _register(self, name: str, inst):
        with self._lock:
            if name in self._instruments:
                if self._logger:
                    self._logger.warn(f"metric {name} already registered")
                return self._instruments[name]
            self._instruments[name] = inst
            return inst

    def has(self, name: str) -> bool:
        """Silent existence check — for idempotent framework registration
        paths (the WARN in _register is for USER double registration, the
        ERROR in _get for using an unregistered metric)."""
        with self._lock:
            return name in self._instruments

    def new_counter(self, name: str, description: str = "") -> Counter:
        return self._register(name, Counter(name, description))

    def new_updown_counter(self, name: str, description: str = "") -> UpDownCounter:
        return self._register(name, UpDownCounter(name, description))

    def new_gauge(self, name: str, description: str = "") -> Gauge:
        return self._register(name, Gauge(name, description))

    def new_histogram(
        self, name: str, description: str = "", buckets: tuple[float, ...] = DEFAULT_HISTOGRAM_BUCKETS
    ) -> Histogram:
        return self._register(name, Histogram(name, description, buckets))

    def _get(self, name: str, kind):
        inst = self._instruments.get(name)
        if inst is None or not isinstance(inst, kind):
            if self._logger:
                self._logger.error(f"metric {name} not registered as {kind.__name__}")
            return None
        return inst

    # Verb API mirroring the reference Manager (register.go:15-25): callers
    # address instruments by name so user code never holds instrument objects.
    def increment_counter(self, name: str, by: float = 1.0, **labels: str) -> None:
        c = self._get(name, Counter)
        if c:
            c.increment(by, **labels)

    def delta_updown_counter(self, name: str, by: float, **labels: str) -> None:
        c = self._get(name, UpDownCounter)
        if c:
            c.delta(by, **labels)

    def record_histogram(self, name: str, value: float, **labels: str) -> None:
        h = self._get(name, Histogram)
        if h:
            h.record(value, **labels)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        g = self._get(name, Gauge)
        if g:
            g.set(value, **labels)

    def histogram(self, name: str) -> Histogram | None:
        return self._get(name, Histogram)

    def gauge_total(self, name: str) -> float:
        """Sum of a gauge across its label sets (0.0 when unregistered).
        Silent like has(): framework health probes read engine gauges
        that only exist once an LLM is registered."""
        with self._lock:
            g = self._instruments.get(name)
        if not isinstance(g, Gauge):
            return 0.0
        return sum(value for _name, _labels, value in g.collect())

    # -- exposition --
    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4."""
        with self._lock:
            instruments = list(self._instruments.values())
        out: list[str] = []
        for inst in instruments:
            name = inst.name  # type: ignore[attr-defined]
            if inst.description:  # type: ignore[attr-defined]
                out.append(f"# HELP {name} {inst.description}")  # type: ignore[attr-defined]
            out.append(f"# TYPE {name} {inst.kind}")  # type: ignore[attr-defined]
            if isinstance(inst, Histogram):
                for key, (counts, total_sum, count) in inst.collect_histogram():
                    base = dict(key)
                    acc = 0
                    for ub, c in zip(inst.buckets, counts):
                        acc += c
                        out.append(_line(f"{name}_bucket", {**base, "le": _fmt(ub)}, acc))
                    acc += counts[-1]
                    out.append(_line(f"{name}_bucket", {**base, "le": "+Inf"}, acc))
                    out.append(_line(f"{name}_sum", base, total_sum))
                    out.append(_line(f"{name}_count", base, count))
            else:
                for mname, labels, value in inst.collect():  # type: ignore[attr-defined]
                    out.append(_line(mname, labels, value))
        return "\n".join(out) + "\n"


def _fmt(v: float) -> str:
    return f"{v:g}"


def _line(name: str, labels: dict[str, str], value) -> str:
    if labels:
        lab = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
        return f"{name}{{{lab}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def new_metrics_manager(logger: Logger | None = None) -> Manager:
    return Manager(logger)
