"""Per-tenant SLO engine: declared latency/availability targets, goodput
counters, and multi-window error-budget burn rates.

The fleet's health question is not "what is p99" but "are we burning the
error budget faster than we can afford" (the SRE multi-window burn-rate
alert). Targets come from config (`TPU_LLM_SLO_TTFT_MS`,
`TPU_LLM_SLO_TPOT_MS`, `TPU_LLM_SLO_AVAILABILITY`) with per-model and
per-adapter overrides via `register_llm(..., slo=...)`. Every finished
request is judged good/bad against its tenant's policy and feeds:

- `app_llm_slo_good_total` / `app_llm_slo_total{model,tenant,priority}` —
  the goodput ratio any dashboard can derive;
- `app_llm_slo_burn_rate{model,window}` — bad-fraction over the window
  divided by the budget (1 - availability target); 1.0 means "burning
  exactly the sustainable rate", 14.4 means "the monthly budget is gone
  in ~2 days";
- `app_llm_slo_fast_burn{model}` — 1 when BOTH the 5m and 1h windows
  exceed the fast-burn threshold (the two-window AND suppresses blips),
  which flips `/.well-known/health` to degraded.

Windows are `metrics.RollingWindow(max_age_s=...)` — time-bounded, so a
burst of failures ages out instead of poisoning the gauge forever.
Gauges zero at engine `close()` AND `_die()` (the dead-engine-gauge
regression class): a dead engine must not hold "fast burn" forever.
"""

from __future__ import annotations

import threading

from . import Manager, RollingWindow

# SRE workbook fast-burn threshold: 14.4x burns a 30-day budget in 2 days.
DEFAULT_FAST_BURN = 14.4
# Minimum judged requests in the short window before fast-burn can trip —
# one bad request out of one must not page.
MIN_FAST_BURN_SAMPLES = 10

_WINDOWS = (("5m", 300.0, 4096), ("1h", 3600.0, 16384))

_REG_LOCK = threading.Lock()


def register_slo_metrics(metrics: Manager) -> None:
    """Idempotent registration (same pattern as register_resilience_metrics)."""
    with _REG_LOCK:
        if not metrics.has("app_llm_slo_total"):
            metrics.new_counter(
                "app_llm_slo_total",
                "requests judged against the SLO policy",
            )
        if not metrics.has("app_llm_slo_good_total"):
            metrics.new_counter(
                "app_llm_slo_good_total",
                "requests that met every declared SLO target",
            )
        if not metrics.has("app_llm_slo_breaches_total"):
            metrics.new_counter(
                "app_llm_slo_breaches_total",
                "individual objective violations (which target burns the budget)",
            )
        if not metrics.has("app_llm_slo_burn_rate"):
            metrics.new_gauge(
                "app_llm_slo_burn_rate",
                "error-budget burn rate over the labelled window (1.0 = sustainable)",
            )
        if not metrics.has("app_llm_slo_fast_burn"):
            metrics.new_gauge(
                "app_llm_slo_fast_burn",
                "1 when both burn windows exceed the fast-burn threshold",
            )


class SLOPolicy:
    """Declared targets. Any subset may be set; unset targets don't judge.
    availability is the good-fraction target (e.g. 0.999): it defines the
    error budget (1 - availability) the burn rate is measured against."""

    __slots__ = ("ttft_ms", "tpot_ms", "availability")

    def __init__(
        self,
        ttft_ms: float | None = None,
        tpot_ms: float | None = None,
        availability: float | None = None,
    ):
        self.ttft_ms = float(ttft_ms) if ttft_ms else None
        self.tpot_ms = float(tpot_ms) if tpot_ms else None
        av = float(availability) if availability else None
        if av is not None:
            av = min(max(av, 0.0), 0.99999)
        self.availability = av

    @classmethod
    def from_config(cls, config) -> "SLOPolicy":
        def _f(key):
            try:
                raw = config.get(key) if config else None
                return float(raw) if raw not in (None, "") else None
            except (TypeError, ValueError):
                return None

        return cls(
            ttft_ms=_f("TPU_LLM_SLO_TTFT_MS"),
            tpot_ms=_f("TPU_LLM_SLO_TPOT_MS"),
            availability=_f("TPU_LLM_SLO_AVAILABILITY"),
        )

    @classmethod
    def coerce(cls, spec) -> "SLOPolicy | None":
        """Accept a policy, a {ttft_ms,tpot_ms,availability} dict, or None."""
        if spec is None or isinstance(spec, cls):
            return spec
        if isinstance(spec, dict):
            return cls(
                ttft_ms=spec.get("ttft_ms"),
                tpot_ms=spec.get("tpot_ms"),
                availability=spec.get("availability"),
            )
        raise TypeError(f"slo spec must be SLOPolicy or dict, got {type(spec)!r}")

    def merged(self, override: "SLOPolicy | None") -> "SLOPolicy":
        if override is None:
            return self
        return SLOPolicy(
            ttft_ms=override.ttft_ms or self.ttft_ms,
            tpot_ms=override.tpot_ms or self.tpot_ms,
            availability=override.availability or self.availability,
        )

    def active(self) -> bool:
        return any(
            v is not None for v in (self.ttft_ms, self.tpot_ms, self.availability)
        )

    def budget(self) -> float:
        """Error budget: the tolerated bad-fraction."""
        return 1.0 - (self.availability if self.availability is not None else 0.999)

    def judge(self, *, ok: bool, ttft_ms: float | None, tpot_ms: float | None) -> bool:
        return not self.violations(ok=ok, ttft_ms=ttft_ms, tpot_ms=tpot_ms)

    def violations(
        self, *, ok: bool, ttft_ms: float | None, tpot_ms: float | None
    ) -> list[str]:
        """Which objectives this request violated (empty = good)."""
        out = []
        if not ok:
            out.append("availability")
        if self.ttft_ms is not None and ttft_ms is not None and ttft_ms > self.ttft_ms:
            out.append("ttft")
        if self.tpot_ms is not None and tpot_ms is not None and tpot_ms > self.tpot_ms:
            out.append("tpot")
        return out

    def to_dict(self) -> dict:
        return {
            "ttft_ms": self.ttft_ms,
            "tpot_ms": self.tpot_ms,
            "availability": self.availability,
        }


class SLOTracker:
    """Per-engine goodput accounting + burn-rate windows for one model
    label. Tenant overrides (adapter name -> SLOPolicy) refine the base
    policy; counters stay per-{model,tenant,priority} while burn gauges
    pool per-model (gauge cardinality stays bounded by fleet size)."""

    def __init__(
        self,
        policy: SLOPolicy,
        metrics: Manager | None,
        label: str,
        tenant_overrides: dict[str, SLOPolicy] | None = None,
        fast_burn_threshold: float = DEFAULT_FAST_BURN,
        clock=None,
    ):
        self.policy = policy
        self.metrics = metrics
        self.label = label
        self.tenant_overrides = dict(tenant_overrides or {})
        self.fast_burn_threshold = float(fast_burn_threshold)
        self._lock = threading.Lock()
        self._windows = {
            name: RollingWindow(size=size, max_age_s=age, clock=clock)
            for name, age, size in _WINDOWS
        }
        self._good = 0
        self._total = 0
        # incident seam (gofr_tpu.flightrec): fired once per 0 -> 1
        # fast-burn transition — the flip is the moment the evidence
        # (which requests burned the budget, what the engine looked
        # like) is still live, so it triggers a black-box bundle.
        self.on_fast_burn = None
        self._fast_burn_prev = False
        if metrics is not None:
            register_slo_metrics(metrics)

    def policy_for(self, tenant: str) -> SLOPolicy:
        return self.policy.merged(self.tenant_overrides.get(tenant))

    def observe(
        self,
        *,
        tenant: str,
        priority: str,
        ok: bool,
        ttft_ms: float | None,
        tpot_ms: float | None,
    ) -> bool:
        """Judge one finished request; returns the good/bad verdict."""
        violated = self.policy_for(tenant).violations(
            ok=ok, ttft_ms=ttft_ms, tpot_ms=tpot_ms
        )
        good = not violated
        with self._lock:
            self._total += 1
            if good:
                self._good += 1
        for w in self._windows.values():
            w.observe(0.0 if good else 1.0)
        if self.metrics is not None:
            labels = {"model": self.label, "tenant": tenant, "priority": priority}
            self.metrics.increment_counter("app_llm_slo_total", **labels)
            if good:
                self.metrics.increment_counter("app_llm_slo_good_total", **labels)
            for objective in violated:
                self.metrics.increment_counter(
                    "app_llm_slo_breaches_total",
                    model=self.label,
                    objective=objective,
                )
            self._publish_gauges()
        return good

    def burn_rates(self) -> dict[str, float]:
        budget = max(self.policy.budget(), 1e-6)
        return {
            name: (w.mean() / budget) for name, w in self._windows.items()
        }

    def fast_burn(self) -> bool:
        """Two-window AND: both 5m and 1h above threshold, with enough
        short-window samples that a single bad request can't page."""
        if len(self._windows["5m"]) < MIN_FAST_BURN_SAMPLES:
            return False
        rates = self.burn_rates()
        return all(r >= self.fast_burn_threshold for r in rates.values())

    def _publish_gauges(self) -> None:
        m = self.metrics
        if m is None:
            return
        for name, rate in self.burn_rates().items():
            m.set_gauge(
                "app_llm_slo_burn_rate", rate, model=self.label, window=name
            )
        fast = self.fast_burn()
        m.set_gauge(
            "app_llm_slo_fast_burn", 1.0 if fast else 0.0, model=self.label
        )
        flipped, self._fast_burn_prev = (
            fast and not self._fast_burn_prev, fast
        )
        if flipped and self.on_fast_burn is not None:
            try:
                self.on_fast_burn()
            except Exception:  # noqa: BLE001 — incident capture is best-effort
                pass

    def zero_gauges(self) -> None:
        """close()/_die() path: a dead engine's burn state must read 0 —
        the dead-engine-gauge regression class. Windows clear too, so a
        restarted engine starts with a clean budget."""
        self._fast_burn_prev = False
        for w in self._windows.values():
            w.clear()
        m = self.metrics
        if m is not None:
            for name, _age, _size in _WINDOWS:
                m.set_gauge(
                    "app_llm_slo_burn_rate", 0.0, model=self.label, window=name
                )
            m.set_gauge("app_llm_slo_fast_burn", 0.0, model=self.label)

    def snapshot(self) -> dict:
        with self._lock:
            good, total = self._good, self._total
        return {
            "policy": self.policy.to_dict(),
            "tenant_overrides": {
                t: p.to_dict() for t, p in sorted(self.tenant_overrides.items())
            },
            "good": good,
            "total": total,
            "goodput": (good / total) if total else 1.0,
            "burn_rates": self.burn_rates(),
            "fast_burn": self.fast_burn(),
            "fast_burn_threshold": self.fast_burn_threshold,
        }


def pool_snapshots(snaps: list[dict]) -> dict:
    """Fleet pooling for ReplicatedLLMEngine.debug_state(): sum goodput,
    max burn (the hottest replica gates health, same as gauge_total on
    the per-replica fast-burn gauge)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {}
    good = sum(s.get("good", 0) for s in snaps)
    total = sum(s.get("total", 0) for s in snaps)
    burn: dict[str, float] = {}
    for s in snaps:
        for w, r in (s.get("burn_rates") or {}).items():
            burn[w] = max(burn.get(w, 0.0), r)
    return {
        "policy": snaps[0].get("policy"),
        "replicas": len(snaps),
        "good": good,
        "total": total,
        "goodput": (good / total) if total else 1.0,
        "burn_rates": burn,
        "fast_burn": any(s.get("fast_burn") for s in snaps),
    }
