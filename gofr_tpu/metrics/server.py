"""Standalone metrics server on METRICS_PORT serving /metrics.

Parity: reference pkg/gofr/metricsServer.go:22-39 (separate HTTP server) and
metrics/handler.go:12-37 (runtime gauges refreshed on each scrape).

Runs on a stdlib ThreadingHTTPServer: scrape traffic is low-rate and must not
contend with the asyncio serving loop.
"""

from __future__ import annotations

import gc
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import Manager

try:
    import resource
except ImportError:  # non-posix
    resource = None  # type: ignore[assignment]


def refresh_runtime_gauges(m: Manager) -> None:
    """Python-runtime analogues of the reference's Go-runtime gauges
    (container.go:166-198: goroutines, heap alloc, numGC, sys)."""
    m.set_gauge("app_python_threads", float(threading.active_count()))
    counts = gc.get_count()
    m.set_gauge("app_python_gc_gen0", float(counts[0]))
    m.set_gauge("app_python_num_gc", float(gc.get_stats()[-1].get("collections", 0)))
    if resource is not None:
        # ru_maxrss is KiB on Linux
        m.set_gauge("app_sys_memory_rss", float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024.0)


class _Handler(BaseHTTPRequestHandler):
    manager: Manager = None  # type: ignore[assignment]

    def do_GET(self):  # noqa: N802
        if self.path.split("?")[0] not in ("/metrics", "/metrics/"):
            self.send_response(404)
            self.end_headers()
            return
        refresh_runtime_gauges(self.manager)
        # OpenMetrics negotiation (how Prometheus asks for exemplars):
        # exemplar suffixes are only legal on the openmetrics content type,
        # so the plain scrape stays byte-compatible 0.0.4.
        accept = self.headers.get("Accept", "")
        openmetrics = "application/openmetrics-text" in accept
        if openmetrics:
            body = self.manager.render_openmetrics().encode("utf-8")
            ctype = "application/openmetrics-text; version=1.0.0; charset=utf-8"
        else:
            body = self.manager.render_prometheus().encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence default stderr access log
        pass


class MetricsServer:
    def __init__(self, manager: Manager, port: int = 2121, host: str = "0.0.0.0"):
        self.manager = manager
        self.port = port
        self.host = host
        self.reuse_port = False  # multi-worker mode: each worker binds
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        handler = type("BoundHandler", (_Handler,), {"manager": self.manager})
        server_cls = ThreadingHTTPServer
        if self.reuse_port:
            server_cls = type(
                "ReusePortHTTPServer", (ThreadingHTTPServer,),
                {"allow_reuse_port": True},
            )
        self._httpd = server_cls((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True, name="gofr-metrics-server")
        self._thread.start()

    def shutdown(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
