"""Multi-host bootstrap: the distributed-communication backend's entry
point.

The reference scales across machines with NCCL/MPI-style app-level
planes; here the collective plane is XLA over ICI (intra-slice) and DCN
(inter-slice), and multi-host just means every process joins one jax
runtime before building its Mesh: `jax.devices()` then enumerates the
GLOBAL device set, the same `make_mesh`/`param_specs` annotations apply
unchanged, and GSPMD routes collectives over ICI within a slice and DCN
across slices. On Cloud TPU pods `jax.distributed.initialize()`
auto-discovers the topology; elsewhere (CPU fleets, tests) the
coordinator is configured explicitly — env convention:

    GOFR_COORDINATOR=host:port   # process 0's address
    GOFR_NUM_PROCESSES=N
    GOFR_PROCESS_ID=i

`tests/test_multihost.py` runs a REAL 2-process CPU cluster through
this path (initialize → global mesh → cross-process collective).
"""

from __future__ import annotations

import os

__all__ = ["init_distributed", "topology", "is_primary"]


def init_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Join (or form) the multi-process jax runtime, then report topology.

    No-op when neither arguments nor env configure a cluster AND the
    platform isn't a TPU pod (single-process mode). Safe to call twice
    (jax raises on re-initialize; already-initialized is not an error
    here — the topology is simply reported).
    """
    import jax

    coordinator = coordinator or os.environ.get("GOFR_COORDINATOR")
    if num_processes is None:
        n = os.environ.get("GOFR_NUM_PROCESSES")
        num_processes = int(n) if n else None
    if process_id is None:
        p = os.environ.get("GOFR_PROCESS_ID")
        process_id = int(p) if p else None

    # jax < 0.6 has no jax.distributed.is_initialized — probe the global
    # state object it wraps, defaulting to "not initialized" if that moves
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        state = getattr(
            getattr(jax._src, "distributed", None), "global_state", None
        )
        already = state is not None and state.client is not None
    else:
        already = is_init()
    if not already:
        if coordinator is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
        elif _tpu_plausible():
            # TPU pods self-discover coordinator/topology from metadata;
            # single-host TPU initializes to a 1-process "cluster". The
            # plausibility check must NOT touch jax.default_backend():
            # evaluating it initializes XLA, after which initialize()
            # always raises — so detect via libtpu/env, and treat a
            # too-late call as single-process rather than crashing.
            try:
                jax.distributed.initialize()
            except (RuntimeError, ValueError):
                pass  # backend already up, or not actually a pod
    return topology()


def _tpu_plausible() -> bool:
    """TPU presence WITHOUT initializing the XLA backend."""
    import importlib.util

    if "tpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        return True
    return importlib.util.find_spec("libtpu") is not None


def topology() -> dict:
    """Global/local device facts for logs, health, and sanity checks."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.default_backend(),
    }


def is_primary() -> bool:
    """True on process 0 — gate checkpoint writes, topic creation, and
    singleton side effects the way rank-0 guards do under MPI."""
    import jax

    return jax.process_index() == 0
