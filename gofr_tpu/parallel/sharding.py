"""Partition specs for the model zoo.

Megatron-style tensor parallelism over the "model" axis:
  - wq / w_gate / w_up: column-parallel (output features sharded)
  - wo / w_down:        row-parallel (input features sharded)
  - embed:          vocab-sharded (logit matmul reduces over model axis)
  - norms:          replicated
KV projections are sharded only when the TP degree divides n_kv_heads —
with MQA (Gemma-2B, n_kv_heads=1) KV is replicated, the standard layout,
so decode all-gathers ride ICI only for Q/O. wkv's output columns pack
heads outermost ([hkv, 2, hd] blocks, transformer._layer_body), so each TP
shard of the flat dim holds whole (k, v) head pairs — never K on one half
of the group and V on the other.

GSPMD inserts the collectives; we only annotate. Specs are pytrees shaped
exactly like the params pytree from models.init_params.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig


def param_specs(
    cfg: TransformerConfig, mesh: Mesh, *, model_axis: str = "model",
    untied: bool = False,
) -> dict:
    tp = mesh.shape.get(model_axis, 1)
    shard_kv = cfg.n_kv_heads % tp == 0 if tp > 1 else True
    m = model_axis if tp > 1 else None
    kv = m if shard_kv else None
    extra = {"unembed": P(m, None)} if untied else {}
    # Qwen2-style qkv biases follow their weight's output-column sharding
    bias = (
        {"bq": P(None, m), "bkv": P(None, kv)}
        if getattr(cfg, "qkv_bias", False)
        else {}
    )
    return {
        **extra,
        "embed": P(m, None),
        "final_norm": P(None),
        "layers": {
            **bias,
            "attn_norm": P(None, None),
            "wq": P(None, None, m),
            "wkv": P(None, None, kv),
            "wo": P(None, m, None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, m),
            "w_up": P(None, None, m),
            "w_down": P(None, m, None),
        },
    }


def mlp_param_specs(params: dict, mesh: Mesh, *, model_axis: str = "model") -> dict:
    """Specs for models.mlp params: alternating column/row parallel (w0
    column, w1 row, …); biases follow their weight's output sharding."""
    tp = mesh.shape.get(model_axis, 1)
    out = {}
    for name in params:
        idx = int(name[1:])
        if tp <= 1:
            out[name] = P() if name.startswith("b") else P(None, None)
        elif name.startswith("w"):
            out[name] = P(None, model_axis) if idx % 2 == 0 else P(model_axis, None)
        else:
            out[name] = P(model_axis) if idx % 2 == 0 else P(None)
    return out


def batch_spec(mesh: Mesh, *, data_axis: str = "data") -> P:
    return P(data_axis if mesh.shape.get(data_axis, 1) > 1 else None)


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """device_put every leaf with its NamedSharding (committed, so later jit
    calls respect the placement without in_shardings plumbing)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: x is None,
    )


def with_shardings(mesh: Mesh, fn, in_specs=None, out_specs=None, **jit_kw):
    """jit fn with NamedSharding-resolved in/out specs (None = infer)."""

    def resolve(tree):
        if tree is None:
            return None
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s) if isinstance(s, P) else s,
            tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    return jax.jit(fn, in_shardings=resolve(in_specs), out_shardings=resolve(out_specs), **jit_kw)
