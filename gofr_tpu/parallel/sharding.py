"""Partition specs for the model zoo.

Megatron-style tensor parallelism over the "model" axis:
  - wq / w_gate / w_up: column-parallel (output features sharded)
  - wo / w_down:        row-parallel (input features sharded)
  - embed:          vocab-sharded (logit matmul reduces over model axis)
  - norms:          replicated
Attention projections shard at WHOLE-HEAD granularity only: q/o when the
TP degree divides n_heads, kv when it divides n_kv_heads — with MQA
(Gemma-2B, n_kv_heads=1) KV is replicated, the standard layout, so decode
all-gathers ride ICI only for Q/O. A shard boundary INSIDE a head is not
just unconventional; on the pinned old-jax CPU stack GSPMD miscompiles
the rope/attention reshapes it induces (tiny config at tp=8: logits off
by ~1.0, cache rows off by ~3.5 — the "old-jax TP prefill drift" that
failed tests/test_parallel.py since PR 2), so head-indivisible degrees
replicate q/o and keep only the MLP/embed sharded. wkv's output columns
pack heads outermost ([hkv, 2, hd] blocks, transformer._layer_body), so
each TP shard of the flat dim holds whole (k, v) head pairs — never K on
one half of the group and V on the other.

GSPMD inserts the collectives; we only annotate. Specs are pytrees shaped
exactly like the params pytree from models.init_params.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig


def param_specs(
    cfg: TransformerConfig, mesh: Mesh, *, model_axis: str = "model",
    untied: bool = False,
) -> dict:
    tp = mesh.shape.get(model_axis, 1)
    shard_kv = cfg.n_kv_heads % tp == 0 if tp > 1 else True
    shard_q = cfg.n_heads % tp == 0 if tp > 1 else True
    m = model_axis if tp > 1 else None
    kv = m if shard_kv else None
    q = m if shard_q else None
    extra = {"unembed": P(m, None)} if untied else {}
    # Qwen2-style qkv biases follow their weight's output-column sharding
    bias = (
        {"bq": P(None, q), "bkv": P(None, kv)}
        if getattr(cfg, "qkv_bias", False)
        else {}
    )
    n_experts = getattr(cfg, "n_experts", 0)
    if n_experts > 0:
        # Expert parallelism over the SAME "model" axis (a TP submesh is
        # the EP group): expert-batched [L, E, ...] weights shard on E
        # when the degree divides the expert count, the router stays
        # replicated. Head-granularity attention sharding is unchanged.
        e = m if (tp > 1 and n_experts % tp == 0) else None
        mlp = {
            "w_router": P(None, None, None),
            "w_gate": P(None, e, None, None),
            "w_up": P(None, e, None, None),
            "w_down": P(None, e, None, None),
        }
    else:
        mlp = {
            "w_gate": P(None, None, m),
            "w_up": P(None, None, m),
            "w_down": P(None, m, None),
        }
    return {
        **extra,
        "embed": P(m, None),
        "final_norm": P(None),
        "layers": {
            **bias,
            "attn_norm": P(None, None),
            "wq": P(None, None, q),
            "wkv": P(None, None, kv),
            "wo": P(None, q, None),
            "mlp_norm": P(None, None),
            **mlp,
        },
    }


def mlp_param_specs(params: dict, mesh: Mesh, *, model_axis: str = "model") -> dict:
    """Specs for models.mlp params: alternating column/row parallel (w0
    column, w1 row, …); biases follow their weight's output sharding."""
    tp = mesh.shape.get(model_axis, 1)
    out = {}
    for name in params:
        idx = int(name[1:])
        if tp <= 1:
            out[name] = P() if name.startswith("b") else P(None, None)
        elif name.startswith("w"):
            out[name] = P(None, model_axis) if idx % 2 == 0 else P(model_axis, None)
        else:
            out[name] = P(model_axis) if idx % 2 == 0 else P(None)
    return out


def batch_spec(mesh: Mesh, *, data_axis: str = "data") -> P:
    return P(data_axis if mesh.shape.get(data_axis, 1) > 1 else None)


def kv_specs(
    cfg: TransformerConfig, mesh: Mesh, *, model_axis: str = "model",
    paged: bool = False,
) -> P:
    """PartitionSpec for the serving engine's KV arrays — the slot slab
    [L, slots, rows, hkv, hd] or the paged block pool
    [L, n_blocks, block, hkv, hd] (same rank, kv-heads at axis 3 either
    way). Sharded along heads when the TP degree divides n_kv_heads;
    REPLICATED under MQA/GQA remainders (the standard layout — with one
    KV head there is nothing to split, and decode all-gathers then ride
    ICI only for Q/O). ``paged`` is accepted for call-site clarity; both
    layouts share the geometry."""
    del paged  # same rank/axis order for the slab and the pool
    tp = mesh.shape.get(model_axis, 1)
    shard = tp > 1 and cfg.n_kv_heads % tp == 0
    return P(None, None, None, model_axis if shard else None, None)


def replicate_gather(mesh: Mesh):
    """Collective-compute overlap seam (docs/advanced-guide/
    sharded-serving.md): returns a pytree transform that forces every
    leaf to the REPLICATED layout inside a jitted program —
    with_sharding_constraint lowers to an all-gather of the leaf's
    shards over ICI. The sharded decode path calls it on the NEXT
    layer's weight shards from inside the layer scan, one layer ahead
    of use: the gather has no data dependency on the current layer's
    matmul, so XLA's async collectives / latency-hiding scheduler
    overlap the two. Gathered-weight compute is also bit-identical to
    the single-device forward (no partial-product psum, hence no
    reduction-order drift) — the TP==TP1 token-equality tests pin it."""

    def gather(tree):
        return jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P())
            ),
            tree,
        )

    return gather


def tp_submeshes(
    cfg: TransformerConfig,
    tp: int,
    *,
    replicas: int | None = None,
    devices: list | None = None,
) -> list[tuple[Mesh, dict]]:
    """Carve the device list into ``replicas`` disjoint tensor-parallel
    submeshes of ``tp`` chips each and pair every mesh with its
    param_specs — the ``meshes=[...]`` input ReplicatedLLMEngine and the
    disaggregated pools take (dp x tp serving from one call). Defaults
    to as many replicas as the devices allow."""
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    tp = max(1, int(tp))
    if replicas is None:
        replicas = len(devices) // tp
    if replicas < 1 or replicas * tp > len(devices):
        raise ValueError(
            f"need {max(1, replicas)} replica(s) x tp={tp} = "
            f"{max(1, replicas) * tp} devices, have {len(devices)}"
        )
    out = []
    for i in range(replicas):
        sub = devices[i * tp : (i + 1) * tp]
        mesh = Mesh(np.asarray(sub).reshape(1, tp), ("data", "model"))
        out.append((mesh, param_specs(cfg, mesh)))
    return out


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """device_put every leaf with its NamedSharding (committed, so later jit
    calls respect the placement without in_shardings plumbing)."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: x is None,
    )


def with_shardings(mesh: Mesh, fn, in_specs=None, out_specs=None, **jit_kw):
    """jit fn with NamedSharding-resolved in/out specs (None = infer)."""

    def resolve(tree):
        if tree is None:
            return None
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s) if isinstance(s, P) else s,
            tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    return jax.jit(fn, in_shardings=resolve(in_specs), out_shardings=resolve(out_specs), **jit_kw)
