"""Sharded training step for the model zoo.

Serving is the north star (BASELINE.json), but the framework ships a real
multi-chip train step: causal-LM cross-entropy, optax optimizer, params
sharded by parallel.sharding's TP rules, batch sharded over "data". GSPMD
derives the gradient psum over "data" and the TP collectives over "model"
from the committed input shardings — no hand-written collectives.

The driver's dryrun_multichip (__graft_entry__.py) compiles and runs this
exact step on an N-virtual-device mesh.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ..models.transformer import TransformerConfig, transformer_forward
from .sharding import batch_spec, param_specs, shard_params


def lm_loss(params: dict, cfg: TransformerConfig, tokens: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy over valid positions. tokens [b,s], mask [b,s]."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    logits, _ = transformer_forward(params, cfg, tokens, positions, kv_mask=mask)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    w = mask[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def make_train_step(
    cfg: TransformerConfig,
    mesh,
    *,
    optimizer: optax.GradientTransformation | None = None,
    learning_rate: float = 3e-4,
) -> tuple[Callable, Callable, Callable]:
    """Returns (shard_fn, init_opt_fn, step_fn).

    shard_fn(params)            -> params placed per TP specs
    init_opt_fn(params)         -> opt_state (sharding inherited from params)
    step_fn(params, opt_state, tokens, mask) -> (params, opt_state, loss)

    Inputs carry committed shardings (device_put), so a bare jit suffices —
    XLA propagates and inserts collectives. Data must be placed with
    batch_spec(mesh) by the caller (parallel.shard_params or device_put).
    """
    opt = optimizer or optax.adamw(learning_rate)

    def shard_fn(params):
        # untied-ness (Llama unembed leaf) lives in the params pytree, not
        # the config — build specs to match what was actually loaded
        specs = param_specs(cfg, mesh, untied="unembed" in params)
        return shard_params(params, mesh, specs)

    def init_opt_fn(params):
        return opt.init(params)

    def step_fn(params, opt_state, tokens, mask):
        loss, grads = jax.value_and_grad(lm_loss)(params, cfg, tokens, mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # compile observatory (gofr_tpu.profiling): the train step is by far
    # the process's largest compile — its registry row is how a dryrun or
    # notebook attributes a multi-second stall to XLA, not the optimizer
    from ..profiling import instrument_jit

    return (
        shard_fn,
        instrument_jit("parallel.init_opt", init_opt_fn, model="train"),
        instrument_jit("parallel.train_step", step_fn, model="train"),
    )


def place_batch(batch: Any, mesh) -> Any:
    from jax.sharding import NamedSharding

    spec = batch_spec(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, NamedSharding(mesh, spec)), batch)
