"""gofr_tpu.parallel — device meshes, shardings, and collectives.

The reference's "distributed backend" is application-level HTTP/gRPC/Kafka
(SURVEY.md §2.8 — no NCCL/MPI). Here the collective plane is XLA over
ICI/DCN: pick a Mesh, annotate params/batch with PartitionSpecs, let GSPMD
insert all-gather/reduce-scatter. Sequence parallelism (ring attention via
shard_map + ppermute) makes long-context first-class.
"""

from .mesh import make_mesh, mesh_shape_for
from .multihost import init_distributed, is_primary, topology
from .ring import ring_attention, ring_prefill
from .sharding import (
    batch_spec,
    kv_specs,
    mlp_param_specs,
    param_specs,
    replicate_gather,
    shard_params,
    tp_submeshes,
    with_shardings,
)
from .pipeline import (
    make_pp_train_step,
    pipeline_layers,
    pp_lm_loss,
    pp_param_shardings,
)
from .train import lm_loss, make_train_step, place_batch

__all__ = [
    "init_distributed",
    "is_primary",
    "topology",
    "pipeline_layers",
    "pp_lm_loss",
    "pp_param_shardings",
    "make_pp_train_step",
    "make_mesh",
    "mesh_shape_for",
    "param_specs",
    "mlp_param_specs",
    "batch_spec",
    "kv_specs",
    "replicate_gather",
    "tp_submeshes",
    "shard_params",
    "with_shardings",
    "ring_attention",
    "ring_prefill",
    "make_train_step",
    "place_batch",
    "lm_loss",
]
