"""Pipeline parallelism: the transformer layer stack sharded by DEPTH over
a mesh axis, microbatches streamed through the stages with activations
hopping stage-to-stage via ppermute.

SURVEY.md §2.8 lists PP as the one optional ("stretch") parallelism row —
the reference is a single-process web framework with no ML execution, so
there is no reference analogue; this is the TPU-native design:

- **Stage = contiguous slice of layers.** Params keep their stacked
  [n_layers, ...] leaves; sharding them P("stage") over the leading axis
  gives each device an [L/S, ...] slice with NO reshapes or per-stage
  param pytrees — the same `lax.scan` layer body as single-device runs
  over the local slice.
- **GPipe schedule inside one `lax.scan`.** T = n_micro + S - 1 ticks;
  at tick t stage 0 injects microbatch t, every stage applies its slice,
  and outputs rotate (i -> i+1) via `lax.ppermute`. All devices run the
  identical program (SPMD) — stage identity is `lax.axis_index`, so the
  schedule compiles to one executable with a collective-permute per tick,
  which XLA overlaps with the next tick's compute on ICI.
- **Autodiff-native.** No hand-written backward: jax transposes the scan
  (reverse-time) and each ppermute (inverse permutation), yielding the
  standard reverse pipeline schedule. `jax.checkpoint` around the stage
  body bounds activation memory to O(local_layers) per microbatch.
- **Bubble** = (S-1)/(n_micro+S-1) idle fraction per pass (GPipe); pick
  n_micro >= 4*S to keep it under ~20%. PP pays off when a model's
  weights + optimizer state exceed one chip's HBM and TP's per-layer
  collectives would cross slow links — stages only ever send one
  activation tensor per tick point-to-point over the ring.

Composes with data parallelism: a ("data", "stage") mesh shards the
microbatch dim over "data" outside shard_map (GSPMD inserts the gradient
psum) while this module owns "stage" inside shard_map.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental namespace, same signature
    from jax.experimental.shard_map import shard_map

try:  # jax >= 0.8: explicit varying-manual-axes cast (the VMA check)
    _pcast = lax.pcast
except AttributeError:  # older jax: shard_map values are varying already

    def _pcast(x, *_a, **_k):
        return x
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerConfig, _embed_tokens, _layer_body, _unembed
from ..ops import rms_norm

__all__ = ["pipeline_layers", "pp_lm_loss", "make_pp_train_step", "pp_param_shardings"]


def _stage_forward(cfg: TransformerConfig, layers_local, x, positions):
    """Run this stage's local layer slice (leaves [L/S, ...]) over x."""

    @jax.checkpoint
    def body(x, lp):
        x, _, _ = _layer_body(
            cfg, x, lp, positions,
            k_cache=None, v_cache=None, cache_length=None, decode=False,
        )
        return x, None

    x, _ = lax.scan(body, x, layers_local)
    return x


def pipeline_layers(
    cfg: TransformerConfig,
    mesh: Mesh,
    axis: str = "stage",
) -> Callable:
    """Returns pp_fn(layers_params, x_mb) -> y_mb.

    layers_params: the model's ["layers"] subtree, leaves [L, ...] sharded
    P(axis) on the leading (layer) axis; L must divide by mesh.shape[axis].
    x_mb: [n_micro, mb, s, d] embedded activations, replicated over axis.
    Returns [n_micro, mb, s, d] last-stage outputs, replicated.
    """
    S = mesh.shape[axis]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def pp_body(layers_local, x_mb):
        idx = lax.axis_index(axis)
        M = x_mb.shape[0]
        T = M + S - 1
        b, s = x_mb.shape[1], x_mb.shape[2]
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        # mark the carries device-varying up front (each stage's state and
        # output buffer genuinely differ) — jax 0.9's vma tracking rejects
        # a scan whose carry starts replicated and becomes varying
        state = _pcast(jnp.zeros(x_mb.shape[1:], x_mb.dtype), (axis,), to="varying")
        out = _pcast(jnp.zeros_like(x_mb), (axis,), to="varying")

        def tick(carry, t):
            state, out = carry
            # stage 0 injects microbatch t (clipped read; drain ticks
            # t >= M re-feed mb M-1, whose recomputed output lands outside
            # the keep window and is discarded)
            inj = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x_in = jnp.where(idx == 0, inj, state)
            y = _stage_forward(cfg, layers_local, x_in, positions)
            # last stage stores tick t's result as microbatch t-(S-1)
            m = t - (S - 1)
            mc = jnp.clip(m, 0, M - 1)
            cur = lax.dynamic_index_in_dim(out, mc, 0, keepdims=False)
            keep = (idx == S - 1) & (m >= 0) & (m < M)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(keep, y, cur), mc, 0
            )
            state = lax.ppermute(y, axis, perm)
            return (state, out), None

        (state, out), _ = lax.scan(tick, (state, out), jnp.arange(T))
        # replicate the last stage's collected outputs to every stage
        out = lax.psum(jnp.where(idx == S - 1, out, jnp.zeros_like(out)), axis)
        return out

    return shard_map(
        pp_body, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
    )


def pp_lm_loss(
    params: dict,
    cfg: TransformerConfig,
    tokens: jnp.ndarray,  # [b, s]
    mask: jnp.ndarray,  # [b, s] True = real token
    pp_fn: Callable,
    n_micro: int,
) -> jnp.ndarray:
    """Causal-LM cross entropy with the layer stack run through pp_fn.
    Embed/final-norm/unembed stay outside the pipeline (replicated): they
    are a single gather + one matmul, not worth a stage."""
    b, s = tokens.shape
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
    x = _embed_tokens(params, cfg, tokens)
    x_mb = x.reshape(n_micro, b // n_micro, s, cfg.d_model)
    y = pp_fn(params["layers"], x_mb).reshape(b, s, cfg.d_model)
    y = rms_norm(y, params["final_norm"], cfg.norm_eps)
    logits = _unembed(params, cfg, y)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    w = mask[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def pp_param_shardings(
    cfg: TransformerConfig, mesh: Mesh, axis: str = "stage",
    untied: bool = False,
):
    """NamedSharding pytree: layer leaves stage-sharded on the leading
    (layer) axis, embed/final_norm (and unembed, if untied) replicated."""
    staged = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    extra = {"unembed": repl} if untied else {}
    layer_keys = [
        "attn_norm", "wq", "wkv", "wo", "mlp_norm",
        "w_gate", "w_up", "w_down",
    ]
    if getattr(cfg, "qkv_bias", False):  # Qwen2: biases are layer leaves too
        layer_keys += ["bq", "bkv"]
    return {
        **extra,
        "embed": repl,
        "final_norm": repl,
        "layers": {k: staged for k in layer_keys},
    }


def make_pp_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    *,
    n_micro: int,
    axis: str = "stage",
    optimizer: optax.GradientTransformation | None = None,
    learning_rate: float = 3e-4,
) -> tuple[Callable, Callable, Callable]:
    """Pipeline-parallel analogue of parallel.train.make_train_step:
    returns (shard_fn, init_opt_fn, step_fn). n_layers must divide by
    mesh.shape[axis]; batch by n_micro."""
    if cfg.n_layers % mesh.shape[axis] != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by {axis}={mesh.shape[axis]}"
        )
    opt = optimizer or optax.adamw(learning_rate)
    pp_fn = pipeline_layers(cfg, mesh, axis)

    def shard_fn(params):
        shardings = pp_param_shardings(
            cfg, mesh, axis, untied="unembed" in params
        )
        return jax.device_put(params, shardings)

    def init_opt_fn(params):
        return opt.init(params)

    def step_fn(params, opt_state, tokens, mask):
        loss, grads = jax.value_and_grad(pp_lm_loss)(
            params, cfg, tokens, mask, pp_fn, n_micro
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # same compile-observatory wrapping as parallel.train.make_train_step
    from ..profiling import instrument_jit

    return (
        shard_fn,
        instrument_jit("parallel.pp_init_opt", init_opt_fn, model="pipeline"),
        instrument_jit("parallel.pp_train_step", step_fn, model="pipeline"),
    )
