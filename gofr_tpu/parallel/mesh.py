"""Device mesh construction.

Axis conventions used across the framework:
  "data"  — data parallelism (batch dim; gradients psum here)
  "model" — tensor parallelism (attention heads / FFN width; ICI all-gathers)
  "seq"   — sequence/context parallelism (ring attention)

On a physical TPU slice jax.make_mesh picks an ICI-friendly device order.
The same code builds CPU meshes under
--xla_force_host_platform_device_count for tests and the driver's
multi-chip dry run.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.5: sharding-in-types axis modes
    from jax.sharding import AxisType, Mesh
except ImportError:  # older jax: meshes are implicitly Auto everywhere
    AxisType = None  # type: ignore[assignment]
    from jax.sharding import Mesh


def mesh_shape_for(n_devices: int, tp: int | None = None) -> dict[str, int]:
    """Default (data, model) factorization: prefer TP across the whole slice
    for serving (weights sharded, batch replicated is wrong for training but
    right for single-host inference); callers override for training."""
    tp = tp or n_devices
    if n_devices % tp:
        raise ValueError(f"tp={tp} does not divide device count {n_devices}")
    return {"data": n_devices // tp, "model": tp}


def make_mesh(
    shape: dict[str, int] | None = None,
    *,
    devices: list | None = None,
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = mesh_shape_for(len(devices))
    n = 1
    for v in shape.values():
        n *= v
    if n != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    # Auto axis types = classic GSPMD: the compiler propagates shardings and
    # inserts collectives from our annotations (explicit mode would demand a
    # jax.set_mesh context at every call site — wrong trade for a framework).
    # On jax builds without AxisType the kwarg is omitted: every mesh is
    # Auto there, so behavior is identical.
    kw = {}
    if AxisType is not None:
        kw["axis_types"] = (AxisType.Auto,) * len(shape)
    return jax.make_mesh(
        tuple(shape.values()),
        tuple(shape.keys()),
        devices=devices,
        **kw,
    )
