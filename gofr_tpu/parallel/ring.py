"""Ring attention: causal attention with the sequence dim sharded over a
mesh axis, K/V rotating around the ring via ppermute while every device
accumulates its queries' online softmax. Memory per device is O(seq/N) and
the K/V transfer overlaps with compute in XLA's pipeline — the TPU-native
answer to long-context, replacing nothing in the reference (which has no
sequence execution, SURVEY.md §5 "Long-context: absent").

Algorithm (blockwise/ring attention, Liu et al. style): each of the N
sequence shards holds q,k,v chunks of the globally-ordered sequence; step t
lets shard i attend to the chunk originally owned by shard (i - t) mod N.
Causality at chunk granularity: skip chunks from later positions, apply the
triangular mask only on the diagonal (t == 0).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: experimental namespace, same signature
    from jax.experimental.shard_map import shard_map

try:  # jax >= 0.8: explicit varying-manual-axes cast (the VMA check)
    _pcast = jax.lax.pcast
except AttributeError:  # older jax: shard_map values are varying already

    def _pcast(x, *_a, **_k):
        return x
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF


def _chunk_attn(q, k, v, scale, mask):
    """q [b,sq,h,d] x k/v [b,sk,h,d] -> (scores-exp sum, max, weighted v).
    mask: None (full) or [sq, sk] bool."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [b,h,q,1]
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m, l, o


def ring_attention(
    q: jnp.ndarray,  # [b, s, h, d] — s sharded over `axis`
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    scale: float | None = None,
    window: int = 0,  # sliding window over GLOBAL positions; 0 = full
) -> jnp.ndarray:
    """Drop-in for multi_head_attention when seq is sharded. GQA: pass K/V
    already expanded to q's head count (ring traffic is the cost anyway).

    window > 0 applies the Mistral band (q_pos - window, q_pos] in global
    coordinates: chunks entirely behind every local query's band are
    skipped at the lax.cond (their rotation still happens — the ring
    schedule is fixed — but their attention math doesn't), and straddling
    chunks get an elementwise band mask. Rows transiently fully-masked in
    a chunk self-correct through the finite-NEG_INF online softmax, the
    same mechanism the flash kernel relies on; the diagonal chunk always
    holds each row's own position, so no row ends fully masked."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis]

    def local(qc, kc, vc):
        axis_idx = jax.lax.axis_index(axis)
        b, sq, h, d = qc.shape
        tri = jnp.tril(jnp.ones((sq, sq), bool))

        # pcast-to-varying: accumulators are per-shard values (device-varying
        # over the ring axis), matching branch outputs under the VMA check.
        m_acc = _pcast(jnp.full((b, h, sq, 1), NEG_INF, jnp.float32), axis, to="varying")
        l_acc = _pcast(jnp.zeros((b, h, sq, 1), jnp.float32), axis, to="varying")
        o_acc = _pcast(jnp.zeros((b, h, sq, d), jnp.float32), axis, to="varying")

        # Static unroll over the ring (n = mesh axis size, known at trace
        # time): lets the diagonal mask be chosen statically and skips the
        # pointless final rotation (n-1 ppermutes, not n).
        for t in range(n):
            src_idx = (axis_idx - t) % n  # chunk owner at this rotation
            # Chunk-level causality: attend iff src chunk is not in the future.
            live = src_idx <= axis_idx if causal else jnp.bool_(True)
            if window > 0:
                # chunk dead iff entirely behind every local query's band:
                # its last global position <= first local q position - window
                band_live = (src_idx + 1) * sq - 1 > axis_idx * sq - window
                live = jnp.logical_and(live, band_live)

            def do(carry_in, kc=kc, vc=vc, t=t, src_idx=src_idx):
                m_acc, l_acc, o_acc = carry_in
                # Diagonal chunk (t == 0) needs the triangular mask; earlier
                # chunks are fully visible (the cond already gated future
                # chunks out) unless a band boundary cuts through them.
                if window > 0:
                    qpos = axis_idx * sq + jnp.arange(sq)[:, None]
                    kpos = src_idx * sq + jnp.arange(sq)[None, :]
                    mask = kpos > qpos - window
                    if causal and t == 0:
                        mask = mask & tri
                else:
                    mask = tri if (causal and t == 0) else None
                m_c, l_c, o_c = _chunk_attn(qc, kc, vc, scale, mask)
                m_new = jnp.maximum(m_acc, m_c)
                a_old = jnp.exp(m_acc - m_new)
                a_new = jnp.exp(m_c - m_new)
                return (
                    m_new,
                    l_acc * a_old + l_c * a_new,
                    o_acc * a_old + o_c * a_new,
                )

            m_acc, l_acc, o_acc = jax.lax.cond(
                live, do, lambda c: c, (m_acc, l_acc, o_acc)
            )
            if t < n - 1:
                # Rotate K/V to the next device; the permute rides ICI.
                perm = [(i, (i + 1) % n) for i in range(n)]
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)

        l_acc = jnp.where(l_acc == 0.0, 1.0, l_acc)
        out = (o_acc / l_acc).astype(qc.dtype)  # [b,h,sq,d]
        return out.transpose(0, 2, 1, 3)

    spec = P(None, axis, None, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


@functools.lru_cache(maxsize=32)
def _ring_prefill_fn(cfg, mesh: Mesh, axis: str, max_cache_len: int):
    """One jitted executable per (cfg, mesh, axis, cache size) — a fresh
    closure per call would miss jax's compile cache and re-trace the whole
    model every prefill."""
    from ..models.transformer import prefill as _prefill

    reps = cfg.n_heads // cfg.n_kv_heads

    window = getattr(cfg, "sliding_window", 0)

    def attn(q, k, v):
        # GQA: expand K/V to q's head count (ring traffic is the cost here
        # and KV is 1/reps of it; see ring_attention docstring)
        if reps > 1:
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
        return ring_attention(
            q, k, v, mesh=mesh, axis=axis, causal=True, window=window
        )

    @jax.jit
    def run(params, tokens, lengths):
        return _prefill(
            params, cfg, tokens, lengths, max_cache_len, prefill_attn=attn
        )

    return run


def ring_prefill(
    params: dict,
    cfg,
    tokens: jnp.ndarray,  # [b, s] right-padded, s sharded over `axis`
    lengths: jnp.ndarray,  # [b]
    *,
    mesh: Mesh,
    axis: str = "seq",
    max_cache_len: int | None = None,
):
    """Long-context sequence-parallel prefill: the FULL transformer forward
    with activations sharded over the sequence axis, attention via
    ring_attention, everything else partitioned by GSPMD from the input
    sharding. Per-device memory is O(s/N) activations + O(s/N) KV — the
    path for prompts whose activations/KV exceed one chip's HBM.

    Returns (last_logits [b, vocab], KVCache) with cache.k/v seq-sharded
    on the cache length axis (reshard/gather to feed single-chip decode,
    or keep sharded for SP decode). max_cache_len defaults to s — pass
    s + decode headroom when the cache will feed decode_step (its
    documented precondition is cache.length < max_len; a headroom-less
    cache from a full-length prompt would silently clamp-overwrite the
    last KV slot).

    s must divide by mesh.shape[axis]. Gemma-2 attn logit soft-capping is
    not supported on the ring path (cap folds into the online softmax
    non-trivially); gemma_2b/llama presets have cap = 0.
    """
    from jax.sharding import NamedSharding

    if getattr(cfg, "attn_logit_cap", 0.0):
        raise NotImplementedError("ring_prefill: attn_logit_cap unsupported")
    n = mesh.shape[axis]
    b, s = tokens.shape
    if s % n != 0:
        raise ValueError(f"seq {s} not divisible by {axis}={n}")

    seq_sharded = NamedSharding(mesh, P(None, axis))
    tokens = jax.device_put(tokens, seq_sharded)
    lengths = jax.device_put(lengths, NamedSharding(mesh, P(None)))
    run = _ring_prefill_fn(cfg, mesh, axis, max_cache_len or s)
    return run(params, tokens, lengths)
