"""CRUD handler generation.

Parity: reference pkg/gofr/crud_handlers.go — AddRESTHandlers(&Entity{}):
reflect over the entity (first annotated field = primary key,
crud_handlers.go:72), derive table name / REST path with overrides
(TableNameOverrider / RestPathOverrider, :37-43), register POST/GET/
GET-by-id/PUT/DELETE with default implementations on the SQL query builder
(:104-278), and let the entity override any verb by defining create /
get_all / get / update / delete methods (:17-35).
"""

from __future__ import annotations

from .http.errors import ErrorEntityNotFound, ErrorInvalidParam
from .utils import snake_case as _snake


def _entity_info(entity_cls: type) -> tuple[str, str, list[str], str]:
    fields = list(getattr(entity_cls, "__annotations__", {}))
    if not fields:
        raise ValueError(f"{entity_cls.__name__} has no annotated fields")
    primary = fields[0]
    table = (
        entity_cls.table_name()
        if hasattr(entity_cls, "table_name")
        else _snake(entity_cls.__name__)
    )
    path = (
        entity_cls.rest_path()
        if hasattr(entity_cls, "rest_path")
        else _snake(entity_cls.__name__)
    )
    return table, path.strip("/"), fields, primary


def register_crud_handlers(app, entity_cls: type) -> None:
    table, path, fields, primary = _entity_info(entity_cls)
    qb_cols = [f for f in fields]

    def _sql(ctx):
        if ctx.sql is None:
            raise ErrorInvalidParam("no SQL datasource configured")
        return ctx.sql

    # -- default implementations (crud_handlers.go:139-278) ----------------
    def create(ctx):
        if hasattr(entity_cls, "create"):
            return entity_cls.create(ctx)
        db = _sql(ctx)
        data = ctx.bind()
        values = [data.get(f) for f in qb_cols]
        db.exec(db.builder.insert(table, qb_cols), *values)
        return f"{entity_cls.__name__} successfully created with id: {data.get(primary)}"

    def get_all(ctx):
        if hasattr(entity_cls, "get_all"):
            return entity_cls.get_all(ctx)
        db = _sql(ctx)
        return db.query(db.builder.select_all(table))

    def get_one(ctx):
        if hasattr(entity_cls, "get"):
            return entity_cls.get(ctx)
        db = _sql(ctx)
        row = db.query_row(db.builder.select_by(table, primary), ctx.path_param("id"))
        if row is None:
            raise ErrorEntityNotFound(primary, ctx.path_param("id"))
        return row

    def update(ctx):
        if hasattr(entity_cls, "update"):
            return entity_cls.update(ctx)
        db = _sql(ctx)
        data = ctx.bind()
        cols = [f for f in qb_cols if f != primary and f in data]
        if not cols:
            raise ErrorInvalidParam("no updatable fields in body")
        args = [data[f] for f in cols] + [ctx.path_param("id")]
        n = db.exec(db.builder.update_by(table, cols, primary), *args)
        if n == 0:
            raise ErrorEntityNotFound(primary, ctx.path_param("id"))
        return f"{entity_cls.__name__} successfully updated with id: {ctx.path_param('id')}"

    def delete(ctx):
        if hasattr(entity_cls, "delete"):
            return entity_cls.delete(ctx)
        db = _sql(ctx)
        n = db.exec(db.builder.delete_by(table, primary), ctx.path_param("id"))
        if n == 0:
            raise ErrorEntityNotFound(primary, ctx.path_param("id"))
        return f"{entity_cls.__name__} successfully deleted with id: {ctx.path_param('id')}"

    app.post(f"/{path}", create)
    app.get(f"/{path}", get_all)
    app.get(f"/{path}/{{id}}", get_one)
    app.put(f"/{path}/{{id}}", update)
    app.delete(f"/{path}/{{id}}", delete)
