"""Offline batch inference over pub/sub: the asynchronous job tier.

The serving stack so far exposes ONE workload shape — a synchronous
request holding an open connection. This subsystem adds the other shape
a production model server carries: fire-and-forget generation jobs at
controlled QoS, drained from a pub/sub topic into the engine's existing
``batch`` priority class (docs/advanced-guide/overload.md), with results
published to a reply topic or POSTed to a webhook, and recurring jobs
scheduled through the framework's cron (docs/advanced-guide/
batch-inference.md).

Durability contract (at-least-once in, exactly-once out):

- A job message is ACKED (committed) only AFTER its result is durably
  published. A crash — or an engine replica kill mid-decode
  (gofr_tpu.resilience.FaultInjector drives this deterministically in
  tests/CI) — leaves the message uncommitted, so the broker redelivers
  and the job runs again.
- Redelivery is made safe by an idempotence ledger keyed on the job id:
  a redelivered job whose result already published is committed and
  skipped, so every job produces EXACTLY ONE published result (the
  ledger is per-process; a consumer joining mid-history should still
  dedup by job id).

Overload ladder (the PR 6 machinery end-to-end): jobs submit at
priority="batch", so brownout clamps their max_new_tokens and
interactive pressure preempts their slots before anything interactive
degrades; an EngineOverloaded shed (429 with Retry-After) PAUSES the
subscriber's pull loop for the advertised backoff instead of hammering
the engine — the batch tier is the fleet's pressure reservoir, never a
second flood.

Backends: every ``gofr_tpu.datasource.pubsub`` backend works. MEMORY
pops on delivery (commit is a no-op), so failed jobs are REPUBLISHED
with an incremented attempt count; FILE/KAFKA/GOOGLE use real committed
offsets, so failure = no commit = broker redelivery. Jobs exceeding
``max_attempts`` go to ``<topic>.dlq`` with the error attached.

Wire format — one JSON object per message::

    {"id": "job_1", "tokens": [1,2,3], "max_new_tokens": 32,
     "temperature": 0.0, "schema": {...}, "reply_topic": "...",
     "webhook": "http://...", "client": "tenant-a", "model": "gemma"}

``prompt`` (text) may replace ``tokens`` when the worker has a
tokenizer; ``schema`` compiles to a grammar-constrained generation
(gofr_tpu.structured). Results mirror the id and carry tokens/text,
finish_reason, and attempt count.

HTTP surface (registered by :func:`attach_batch_worker`): submit/poll in
the ``/v1/batches`` style — POST enqueues over the same topic, GET polls
the worker's result ledger.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from typing import Any, Callable

__all__ = [
    "BatchJob",
    "BatchStore",
    "BatchWorker",
    "attach_batch_worker",
]

_TERMINAL_OK = ("eos", "length")


class BatchJob:
    """One parsed generation job. Malformed payloads raise ValueError —
    they go straight to the DLQ (redelivering a parse error forever
    would wedge the topic)."""

    def __init__(self, data: dict):
        if not isinstance(data, dict):
            raise ValueError("job payload must be a JSON object")
        self.id = str(data.get("id") or f"job_{uuid.uuid4().hex[:12]}")
        self.model = data.get("model") or ""
        self.tokens = data.get("tokens")
        self.prompt = data.get("prompt")
        if self.tokens is None and self.prompt is None:
            raise ValueError("job needs 'tokens' or 'prompt'")
        if self.tokens is not None and (
            not isinstance(self.tokens, list)
            or not all(isinstance(t, int) for t in self.tokens)
        ):
            raise ValueError("'tokens' must be a list of ints")
        self.max_new_tokens = int(data.get("max_new_tokens", 32))
        self.temperature = float(data.get("temperature", 0.0))
        self.schema = data.get("schema")
        self.reply_topic = data.get("reply_topic") or ""
        self.webhook = data.get("webhook") or ""
        self.client = str(data.get("client") or "")
        self.session = str(data.get("session") or "")
        self.attempt = int(data.get("_attempt", 0))
        # W3C trace context injected at publish (submit edge / cron tick):
        # the worker resumes it, so the async hop does not shatter the
        # submitter's journey. Rides `raw`, so requeues and DLQ re-walks
        # keep carrying it.
        self.traceparent = str(data.get("traceparent") or "") or None
        self.raw = dict(data)

    @classmethod
    def from_payload(cls, payload: bytes) -> "BatchJob":
        return cls(json.loads(payload))


class BatchStore:
    """Bounded in-memory job ledger: idempotence for redeliveries plus
    the /v1/batches poll surface. Oldest finished entries evict first;
    in-flight/pending entries are never evicted (they gate dedup)."""

    def __init__(self, cap: int = 4096):
        self.cap = cap
        self._lock = threading.Lock()
        self._jobs: dict[str, dict] = {}
        self._batches: dict[str, list[str]] = {}

    def register(self, job_id: str, batch_id: str | None = None) -> None:
        with self._lock:
            self._jobs.setdefault(job_id, {
                "id": job_id, "status": "queued", "attempts": 0,
                "result": None, "error": None,
            })
            if batch_id is not None:
                self._batches.setdefault(batch_id, []).append(job_id)
            self._evict_locked()

    def state(self, job_id: str) -> dict | None:
        with self._lock:
            st = self._jobs.get(job_id)
            return dict(st) if st is not None else None

    def begin(self, job_id: str) -> tuple[bool, int]:
        """Claim a job for processing. Returns (claimed, attempt#):
        claimed=False when it is already running or already done — the
        redelivery/duplicate-pull guard."""
        with self._lock:
            st = self._jobs.setdefault(job_id, {
                "id": job_id, "status": "queued", "attempts": 0,
                "result": None, "error": None,
            })
            if st["status"] in ("running", "ok"):
                return False, st["attempts"]
            st["status"] = "running"
            st["attempts"] += 1
            return True, st["attempts"]

    def unclaim(self, job_id: str, error: str | None = None) -> None:
        """Give a claim back WITHOUT consuming the attempt: the
        pressure path (engine shed / drain / fleet-restart window)
        requeues the job, and billing those cycles against max_attempts
        would dead-letter a healthy job during one rebuild window."""
        with self._lock:
            st = self._jobs.get(job_id)
            if st is None:
                return
            st["status"] = "queued"
            st["attempts"] = max(0, st["attempts"] - 1)
            st["error"] = error

    def finish(self, job_id: str, *, ok: bool, result: dict | None = None,
               error: str | None = None, final: bool = False) -> None:
        with self._lock:
            st = self._jobs.get(job_id)
            if st is None:
                return
            st["status"] = "ok" if ok else ("dlq" if final else "queued")
            st["result"] = result
            st["error"] = error
            self._evict_locked()

    def batch_view(self, batch_id: str) -> dict | None:
        with self._lock:
            ids = self._batches.get(batch_id)
            if ids is None:
                return None
            jobs = {j: dict(self._jobs.get(j) or {"status": "expired"}) for j in ids}
        counts: dict[str, int] = {}
        for st in jobs.values():
            counts[st.get("status", "expired")] = (
                counts.get(st.get("status", "expired"), 0) + 1
            )
        done = counts.get("ok", 0) + counts.get("dlq", 0)
        return {
            "id": batch_id,
            "object": "batch",
            "status": "completed" if done == len(jobs) else (
                "in_progress" if counts.get("running") else "queued"
            ),
            "counts": counts,
            "jobs": jobs,
        }

    def _evict_locked(self) -> None:
        # finished first, then never-claimed queued entries (a flood of
        # POST /v1/batches registrations must not grow without bound);
        # running entries are never evicted — they gate redelivery dedup
        if len(self._jobs) > self.cap:
            for status_class in (("ok", "dlq"), ("queued",)):
                for jid in list(self._jobs):
                    if self._jobs[jid]["status"] in status_class:
                        del self._jobs[jid]
                    if len(self._jobs) <= self.cap:
                        break
                if len(self._jobs) <= self.cap:
                    break
        while len(self._batches) > self.cap:
            self._batches.pop(next(iter(self._batches)))


class BatchWorker:
    """Drains one pub/sub topic of generation jobs into an LLM engine's
    batch priority class with bounded in-flight concurrency.

    ``run()`` is an asyncio coroutine the app schedules at serve()
    (attach_batch_worker wires it); generation itself runs on executor
    threads — the engine's blocking stream consumption must not park the
    event loop."""

    def __init__(
        self,
        container,
        topic: str,
        *,
        model: str = "",
        reply_topic: str = "",
        concurrency: int = 4,
        max_attempts: int = 3,
        tokenizer: Any = None,
        poll_timeout: float = 0.5,
        store: BatchStore | None = None,
        webhook_timeout: float = 10.0,
    ):
        self.container = container
        self.topic = topic
        self.model = model
        self.reply_topic = reply_topic or f"{topic}.results"
        self.dlq_topic = f"{topic}.dlq"
        self.concurrency = max(1, int(concurrency))
        self.max_attempts = max(1, int(max_attempts))
        self.tokenizer = tokenizer
        self.poll_timeout = poll_timeout
        self.webhook_timeout = webhook_timeout
        self.store = store if store is not None else BatchStore()
        self.logger = container.logger
        self.metrics = container.metrics_manager
        self.tracer = getattr(container, "tracer", None)
        self._grammar_vocab = None  # lazy (tokenizer -> byte vocab)
        self._inflight: set[str] = set()
        self._lock = threading.Lock()
        self._pause_until = 0.0  # engine-shed pull backoff (monotonic)
        self._stopped = False
        self.jobs_ok = 0
        self.jobs_error = 0
        self.jobs_requeued = 0
        self.jobs_dlq = 0
        self.jobs_deduped = 0
        if self.metrics is not None:
            if not self.metrics.has("app_llm_batch_jobs_total"):
                self.metrics.new_counter(
                    "app_llm_batch_jobs_total",
                    "offline batch generation jobs by outcome "
                    "(ok|error|requeued|dlq|dedup)",
                )
            if not self.metrics.has("app_llm_batch_queue_depth"):
                self.metrics.new_gauge(
                    "app_llm_batch_queue_depth",
                    "batch jobs pulled and not yet finished (in-flight "
                    "against the engine; zeroed at worker close)",
                )

    # -- engine resolution + job execution --------------------------------

    def _engine(self, job: BatchJob):
        name = job.model or self.model
        if not name:
            raise ValueError("job names no model and worker has no default")
        return self.container.tpu().llm(name)

    def _grammar_for(self, job: BatchJob):
        if job.schema is None:
            return None
        if self.tokenizer is None:
            raise ValueError(
                "schema-constrained job needs a worker tokenizer "
                "(attach_batch_worker(tokenizer=...))"
            )
        from ..structured import grammar_cache, vocab_from_tokenizer

        if self._grammar_vocab is None:
            self._grammar_vocab = vocab_from_tokenizer(self.tokenizer)
        eos = getattr(self.tokenizer, "eos_id", None)
        if eos is None:
            raise ValueError("tokenizer has no eos_id; cannot close a grammar")
        return grammar_cache.get(job.schema, self._grammar_vocab, int(eos))

    def _run_job(self, job: BatchJob) -> dict:
        """Blocking generation (executor thread). Raises EngineOverloaded
        through — the caller turns it into pull backoff, not a failure."""
        from ..llm import GenRequest

        handle = self._engine(job)
        grammar = self._grammar_for(job)
        if job.tokens is not None:
            toks = list(job.tokens)
            eos = -1 if grammar is None else grammar.eos_id
        else:
            if self.tokenizer is None:
                raise ValueError(
                    "text job needs a worker tokenizer "
                    "(attach_batch_worker(tokenizer=...))"
                )
            toks = self.tokenizer.encode(job.prompt)
            eos = self.tokenizer.eos_id if self.tokenizer.eos_id is not None else -1
        # Resume the submitter's trace across the pub/sub hop: the batch.job
        # span parents to the traceparent the publish edge injected (or a
        # cron tick's), so the journey survives the async boundary — and the
        # engine's llm.request span nests under it via req.traceparent.
        tp = job.traceparent
        jspan = None
        if self.tracer is not None:
            from ..tracing import parse_traceparent

            jspan = self.tracer.start_detached_span(
                "batch.job",
                parent=parse_traceparent(tp) if tp else None,
                attributes={
                    "batch.job_id": job.id,
                    "batch.topic": self.topic,
                    "batch.attempt": job.attempt,
                },
            )
            tp = jspan.traceparent
        try:
            req = handle.submit(GenRequest(
                toks,
                max_new_tokens=job.max_new_tokens,
                temperature=job.temperature,
                eos_token=eos,
                priority="batch",  # the overload ladder's pressure reservoir
                client=job.client,
                session_id=job.session,
                grammar=grammar,
                traceparent=tp,
            ))
            out = req.tokens(timeout=300.0)
        except BaseException as e:
            if jspan is not None:
                jspan.set_attribute("error", repr(e))
                jspan.set_status("ERROR")
                jspan.end()
            raise
        if jspan is not None:
            jspan.set_attribute("batch.finish_reason", req.finish_reason or "")
            if req.finish_reason not in _TERMINAL_OK:
                jspan.set_status("ERROR")
            jspan.end()
        if req.finish_reason not in _TERMINAL_OK:
            raise RuntimeError(
                f"generation finished {req.finish_reason!r}"
            )
        result = {
            "id": job.id,
            "object": "batch.result",
            "status": "ok",
            "model": job.model or self.model,
            "tokens": out,
            "finish_reason": req.finish_reason,
            "n_tokens": len(out),
        }
        if self.tokenizer is not None:
            try:
                result["text"] = self.tokenizer.decode(out)
            except Exception:  # noqa: BLE001 — ids are the contract, text is a courtesy
                pass
        return result

    # -- result publication (the ack gate) --------------------------------

    def _publish_result(self, job: BatchJob, result: dict) -> None:
        """Durably publish BEFORE ack: webhook when the job names one,
        else the reply topic. Raising here leaves the job uncommitted —
        redelivery retries the publish, and the idempotence ledger keeps
        the engine work from running twice."""
        payload = json.dumps(result).encode()
        if job.webhook:
            self._post_webhook(job.webhook, payload)
            return
        self.container.pubsub.publish_sync(
            job.reply_topic or self.reply_topic, payload
        )

    def _post_webhook(self, url: str, payload: bytes) -> None:
        import urllib.request

        req = urllib.request.Request(
            url, data=payload,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.webhook_timeout) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"webhook {url} answered {resp.status}")

    # -- the drain loop ----------------------------------------------------

    def _count(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_batch_jobs_total", topic=self.topic, outcome=outcome
            )

    def _depth(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_batch_queue_depth", float(len(self._inflight)),
                topic=self.topic,
            )

    async def run(self) -> None:
        """The subscriber loop: pull -> claim -> process (bounded
        concurrency) -> publish -> ack. Cancellation (app shutdown) exits
        cleanly and zeros the depth gauge."""
        pubsub = self.container.pubsub
        if pubsub is None:
            if self.logger is not None:
                self.logger.error(
                    "batch worker: no pub/sub backend (set PUBSUB_BACKEND)"
                )
            return
        # a fresh serve() re-invokes run(): only close() stops the worker
        # for good, a cancelled previous loop must not latch _stopped
        self._stopped = False
        sem = asyncio.Semaphore(self.concurrency)
        loop = asyncio.get_running_loop()
        tasks: set[asyncio.Task] = set()
        try:
            while not self._stopped:
                now = time.monotonic()
                if now < self._pause_until:
                    # engine shed us (429 + Retry-After): the batch tier
                    # obeys the price instead of re-offering the load
                    await asyncio.sleep(min(self._pause_until - now, 1.0))
                    continue
                await sem.acquire()
                sem.release()
                try:
                    msg = await pubsub.subscribe(self.topic, self.poll_timeout)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — broker hiccup
                    if self.logger is not None:
                        self.logger.error(f"batch subscribe error: {e!r}")
                    await asyncio.sleep(1.0)
                    continue
                if msg is None:
                    continue
                try:
                    job = BatchJob.from_payload(msg.value)
                except (ValueError, json.JSONDecodeError) as e:
                    err = str(e)
                    await loop.run_in_executor(
                        None,
                        lambda m=msg, s=err: (
                            self._to_dlq_raw(m.value, s), m.commit()
                        ),
                    )
                    self._count("dlq")
                    continue
                # classify under the lock, ACT after releasing it — an
                # await (or a blocking commit) while holding a lock the
                # _process finally-block also takes on this event loop
                # would deadlock the whole worker
                with self._lock:
                    st = self.store.state(job.id)
                    dup_running = job.id in self._inflight or (
                        st is not None and st["status"] == "running"
                    )
                    already_ok = (
                        not dup_running
                        and st is not None and st["status"] == "ok"
                    )
                    if not dup_running and not already_ok:
                        self._inflight.add(job.id)
                        self._depth()
                if dup_running:
                    # concurrent duplicate delivery (offset backends
                    # re-serve uncommitted records): leave uncommitted,
                    # let the claimed owner ack it
                    await asyncio.sleep(0.05)
                    continue
                if already_ok:
                    # idempotence ledger: result already published —
                    # ack the redelivery, do NOT regenerate
                    await loop.run_in_executor(None, msg.commit)
                    self.jobs_deduped += 1
                    self._count("dedup")
                    continue
                await sem.acquire()
                t = loop.create_task(self._process(sem, msg, job))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        finally:
            self._stopped = True
            for t in tasks:
                t.cancel()
            if self.metrics is not None:
                self.metrics.set_gauge(
                    "app_llm_batch_queue_depth", 0.0, topic=self.topic
                )

    async def _process(self, sem: asyncio.Semaphore, msg, job: BatchJob) -> None:
        from ..llm import EngineDraining, EngineOverloaded, EngineStoppedError

        loop = asyncio.get_running_loop()
        try:
            claimed, attempt = self.store.begin(job.id)
            if not claimed:
                # finished between pull and claim: ack if published
                st = self.store.state(job.id)
                if st is not None and st["status"] == "ok":
                    await loop.run_in_executor(None, msg.commit)
                    self.jobs_deduped += 1
                    self._count("dedup")
                return
            try:
                result = await loop.run_in_executor(None, self._run_job, job)
            except (EngineOverloaded, EngineDraining, EngineStoppedError) as e:
                # overload shed, rolling deploy, or a fleet mid-restart
                # (replica kill -> supervisor rebuild window): back the
                # PULL RATE off — Retry-After when the engine priced one,
                # a short probe interval otherwise — and put the job back
                # (no commit / republish). This is pressure, not failure:
                # it does not consume an attempt.
                retry = float(getattr(e, "retry_after", None) or 1.0)
                self._pause_until = max(
                    self._pause_until, time.monotonic() + retry
                )
                # unclaim, not finish: begin() billed an attempt at claim
                # time, and pressure cycles must not consume the budget
                self.store.unclaim(job.id, error=str(e))
                await loop.run_in_executor(
                    None, self._requeue, msg, job, False
                )
                self.jobs_requeued += 1
                self._count("requeued")
                return
            except asyncio.CancelledError:
                self.store.finish(job.id, ok=False, error="worker stopped")
                raise
            except Exception as e:  # noqa: BLE001 — job failure path
                # _fail commits / republishes (broker I/O): off the loop
                await loop.run_in_executor(
                    None, self._fail, msg, job, attempt, str(e)
                )
                return
            try:
                await loop.run_in_executor(
                    None, self._publish_result, job, result
                )
            except Exception as e:  # noqa: BLE001 — publish failure = retry
                await loop.run_in_executor(
                    None, self._fail, msg, job, attempt,
                    f"result publish failed: {e!r}",
                )
                return
            # ack only now: result is durably out. The commit is broker
            # I/O (offset write / Kafka round trip) — executor, so a slow
            # broker never parks the serving app's event loop
            self.store.finish(job.id, ok=True, result=result)
            await loop.run_in_executor(None, msg.commit)
            self.jobs_ok += 1
            self._count("ok")
        finally:
            with self._lock:
                self._inflight.discard(job.id)
                self._depth()
            sem.release()

    def _fail(self, msg, job: BatchJob, attempt: int, error: str) -> None:
        self.jobs_error += 1
        self._count("error")
        if self.logger is not None:
            self.logger.error(
                f"batch job {job.id} attempt {attempt} failed: {error}"
            )
        # retry backoff rides the pull-pause: an immediate re-pull of the
        # same (or next) record during a transient outage is a retry
        # storm that burns the whole attempt budget inside one failure
        # window (a replica-rebuild takes seconds; 20 instant retries
        # take milliseconds)
        self._pause_until = max(
            self._pause_until,
            time.monotonic() + min(0.5 * attempt, 10.0),
        )
        if attempt >= self.max_attempts:
            self._to_dlq_raw(
                json.dumps({**job.raw, "_error": error}).encode(), error
            )
            self.store.finish(job.id, ok=False, error=error, final=True)
            msg.commit()  # poisoned job must not wedge the topic
            self.jobs_dlq += 1
            self._count("dlq")
            return
        self.store.finish(job.id, ok=False, error=error)
        self._requeue(msg, job, True)

    def _requeue(self, msg, job: BatchJob, consume_attempt: bool) -> None:
        """Give the job back to the broker. Offset backends redeliver the
        uncommitted record by themselves; MEMORY pops on delivery, so the
        payload is republished explicitly (attempt count rides the
        payload there — the store's count is per-process)."""
        if getattr(msg, "_committer", None) is None:
            payload = dict(job.raw)
            if consume_attempt:
                payload["_attempt"] = job.attempt + 1
            self.container.pubsub.publish_sync(
                self.topic, json.dumps(payload).encode()
            )

    def _to_dlq_raw(self, payload: bytes, error: str) -> None:
        try:
            self.container.pubsub.publish_sync(self.dlq_topic, payload)
        except Exception as e:  # noqa: BLE001 — DLQ publish is best-effort
            if self.logger is not None:
                self.logger.error(f"batch DLQ publish failed: {e!r}")

    def close(self) -> None:
        self._stopped = True
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_batch_queue_depth", 0.0, topic=self.topic
            )

    def stats(self) -> dict:
        return {
            "topic": self.topic,
            "reply_topic": self.reply_topic,
            "concurrency": self.concurrency,
            "inflight": len(self._inflight),
            "ok": self.jobs_ok,
            "error": self.jobs_error,
            "requeued": self.jobs_requeued,
            "dlq": self.jobs_dlq,
            "deduped": self.jobs_deduped,
            "paused_s": max(0.0, self._pause_until - time.monotonic()),
        }


# ---------------------------------------------------------------------------
# app wiring: routes + background task + cron
# ---------------------------------------------------------------------------

def attach_batch_worker(
    app,
    topic: str,
    *,
    model: str = "",
    cron_jobs: list[tuple[str, str, dict]] | None = None,
    **worker_kw,
) -> BatchWorker:
    """Wire a BatchWorker into a gofr_tpu App:

    - the drain loop runs as an app background task (starts at serve(),
      cancelled at shutdown),
    - ``POST /v1/batches`` submits jobs over the same topic (one body =
      one batch of jobs) and ``GET /v1/batches/{id}`` polls the ledger,
    - each ``(schedule, name, job_template)`` in ``cron_jobs`` publishes
      a fresh job on the framework cron (recurring evaluations, nightly
      summarization sweeps — the GoFr AddCronJob surface feeding the
      same durable queue).

    Unset worker kwargs default from app config: TPU_LLM_BATCH_CONCURRENCY,
    TPU_LLM_BATCH_MAX_ATTEMPTS, TPU_LLM_BATCH_REPLY_TOPIC
    (docs/references/configs.md).
    """
    cfg = app.config
    worker_kw.setdefault(
        "concurrency", cfg.get_int("TPU_LLM_BATCH_CONCURRENCY", 4)
    )
    worker_kw.setdefault(
        "max_attempts", cfg.get_int("TPU_LLM_BATCH_MAX_ATTEMPTS", 3)
    )
    worker_kw.setdefault(
        "reply_topic", cfg.get_or_default("TPU_LLM_BATCH_REPLY_TOPIC", "")
    )
    worker = BatchWorker(app.container, topic, model=model, **worker_kw)
    app.add_background_task(worker.run)

    def submit_batch(ctx):
        if app.container.pubsub is None:
            from ..http.errors import ErrorServiceUnavailable

            raise ErrorServiceUnavailable("no pub/sub backend configured")
        body = ctx.bind()
        jobs = body.get("jobs")
        if not isinstance(jobs, list) or not jobs:
            from ..http.errors import ErrorInvalidParam

            raise ErrorInvalidParam("jobs")
        batch_id = f"batch_{uuid.uuid4().hex[:12]}"
        # Inject the caller's trace context into every envelope: the worker
        # resumes it, so a /v1/batches submit and its eventual generation
        # stitch into one journey even across the durable queue.
        from ..tracing import current_span

        cs = current_span()
        tp = cs.traceparent if cs is not None and cs.end_ns == 0 else None
        ids = []
        for data in jobs:
            try:
                job = BatchJob(dict(data))
            except (ValueError, TypeError) as e:
                from ..http.errors import HTTPError

                err = HTTPError(f"invalid job: {e}")
                err.status_code = 400
                raise err from e
            worker.store.register(job.id, batch_id)
            env = job.raw | {"id": job.id}
            if tp and not env.get("traceparent"):
                env["traceparent"] = tp
            app.container.pubsub.publish_sync(
                topic, json.dumps(env).encode()
            )
            ids.append(job.id)
        from ..http.responder import Response, to_json_bytes

        return Response(200, [("Content-Type", "application/json")], to_json_bytes({
            "id": batch_id,
            "object": "batch",
            "status": "queued",
            "jobs": ids,
            "poll": f"/v1/batches/{batch_id}",
        }))

    def poll_batch(ctx):
        view = worker.store.batch_view(ctx.path_param("id"))
        if view is None:
            from ..http.errors import ErrorEntityNotFound

            raise ErrorEntityNotFound("batch", ctx.path_param("id"))
        from ..http.responder import Response, to_json_bytes

        # raw body (no {"data": ...} envelope): /v1/* speaks the
        # OpenAI-style dialect end-to-end
        return Response(
            200, [("Content-Type", "application/json")], to_json_bytes(view)
        )

    def worker_stats(_ctx):
        return worker.stats()

    app.post("/v1/batches", submit_batch)
    app.get("/v1/batches/{id}", poll_batch)
    app.get("/v1/batches-stats", worker_stats)

    counter = {"n": 0}
    for schedule, name, template in cron_jobs or []:
        def make_job(template=template, name=name):
            def publish_job(_ctx):
                counter["n"] += 1
                payload = dict(template)
                payload.setdefault("id", f"{name}_{counter['n']}")
                # A cron-published job's journey starts at the cron tick:
                # mint a root span here and inject its traceparent so the
                # worker's batch.job span parents to the tick, not nothing.
                tracer = getattr(app, "tracer", None)
                if tracer is not None and not payload.get("traceparent"):
                    tick = tracer.start_detached_span(
                        "batch.cron_tick",
                        attributes={"cron.job": name, "batch.topic": topic},
                    )
                    payload["traceparent"] = tick.traceparent
                    tick.end()
                app.container.pubsub.publish_sync(
                    topic, json.dumps(payload).encode()
                )
            return publish_job

        app.add_cron_job(schedule, name, make_job())
    return worker
