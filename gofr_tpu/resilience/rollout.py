"""Zero-downtime model rollouts: live weight reload, canary-gated
traffic shift, automatic rollback.

The one operation a production LLM fleet performs constantly — shipping
a new checkpoint — used to require killing the process: ``register_llm``
bound one immutable ``(cfg, params)`` for the process lifetime. This
module is GoFr's data-migration + config-reload + circuit-breaker
probe/reintegrate story applied to *weights*: load v(N+1), prove it
healthy, shift traffic onto it one replica at a time, and roll back
automatically on regression — with zero dropped requests and no stream
ever served tokens from two model versions.

Pieces:

- :class:`RolloutController` — the fleet state machine
  (``shifting -> baking -> completed`` | ``rolling_back ->
  rolled_back``). One replica at a time it: drains the replica (PR 5
  drain semantics, per-replica instead of per-process — in-flight
  requests FINISH on the old weights), closes it, rebuilds it on the
  staged version through the supervisor's ``_build_replica`` seam,
  gates the candidate with the PR 7 canary probe (version-keyed
  references) **plus** a shadow-traffic replay (a few real prompts
  re-run for completion/vocabulary sanity — not token equality, new
  weights legitimately differ), and only then admits it to routing.
  After the last replica shifts, a bake window
  (``TPU_LLM_ROLLOUT_BAKE_S``) watches for regressions — a replica
  death, a numerical-watchdog trip, a device quarantine, a
  request-error delta, or the ``rollout_bake_regression`` fault point —
  and a trip halts everything and rolls every upgraded replica back to
  the retained old params. The fleet always ends fully on ONE version.
- :class:`ModelHandle` — what ``register_llm`` returns and
  ``ctx.tpu().llm(name)`` resolves: the versioned registry entry. It
  proxies the full engine surface (existing callers are unchanged) and
  adds ``deploy(cfg, params, version=...)``. For a replicated fleet,
  deploy delegates to the fleet's rollout controller; for a bare
  single engine it runs a blue-green SWAP instead (build the new
  engine next to the old one, gate it, atomically repoint the handle,
  drain the old engine in the background, watch the same bake window,
  and swap back on regression) — zero downtime either way, at the cost
  of two resident weight copies during the swap.
- Typed errors carrying the HTTP-status seam: a malformed deploy is a
  4xx at the admin route (``POST /.well-known/debug/rollout``), a
  concurrent deploy a 409 — never a dead replica or a masked 500.

Mid-stream version pinning lives in ``gofr_tpu.llm`` (failover pins a
request that has emitted tokens to a same-version replica, else errors
cleanly); the checkpoint structure/shape/dtype validation lives in
``gofr_tpu.models.checkpoint.validate_params``. Knobs and the failure
model: docs/advanced-guide/rollouts.md.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "ModelHandle",
    "RolloutController",
    "RolloutError",
    "RolloutInProgress",
]


class RolloutError(RuntimeError):
    """A deploy request that cannot be staged (bad arguments, duplicate
    version label, no params). 400 via the statusCodeResponder seam —
    operator error, not an engine failure."""

    status_code = 400


class RolloutInProgress(RolloutError):
    """A deploy was staged while another rollout is still shifting,
    baking, or rolling back. 409: retry after the active rollout
    reaches a terminal state."""

    status_code = 409


# state -> app_llm_rollout_state gauge value. Terminal states read 0
# (nothing in progress); the counters say how each rollout ended.
ROLLOUT_STATE_GAUGE = {
    "idle": 0.0,
    "shifting": 1.0,
    "baking": 2.0,
    "rolling_back": 3.0,
    "completed": 0.0,
    "rolled_back": 0.0,
    "aborted": 0.0,
}

_ACTIVE_STATES = ("idle", "shifting", "baking", "rolling_back")

SHADOW_MAX_NEW = 8  # tokens per shadow-probe replay (sanity, not equality)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def shadow_probe(candidate, prompts, *, max_new: int = SHADOW_MAX_NEW,
                 timeout: float = 60.0, adapter: str = "",
                 tracer=None) -> tuple[bool, str]:
    """Replay a few REAL prompts on a not-yet-routed candidate engine
    and judge sanity only: the stream must complete (``max_new`` tokens
    — no eos is set, a short stream means a dying engine) and stay
    inside the vocabulary (the numerical-watchdog sentinel ``-1`` is
    out-of-vocabulary by construction). Token equality is deliberately
    NOT checked — a new model version legitimately answers differently;
    what must not change is that it answers at all. ``adapter`` routes
    the replay through a STAGED LoRA adapter on a live engine (the
    adapter hot-load gate: the candidate is a table row, not an
    engine). With ``tracer``, the whole replay is one
    ``rollout.shadow_replay`` journey and each probe's engine spans nest
    under it — a failed gate is debuggable from the trace store like any
    other request."""
    from ..llm import GenRequest

    gate_span = None
    tp = None
    if tracer is None:
        tracer = getattr(candidate, "tracer", None)
    if tracer is not None:
        gate_span = tracer.start_detached_span(
            "rollout.shadow_replay",
            attributes={
                "rollout.probes": len(list(prompts)),
                "rollout.adapter": adapter,
            },
        )
        tp = gate_span.traceparent

    def _verdict(ok: bool, detail: str) -> tuple[bool, str]:
        if gate_span is not None:
            gate_span.set_attribute("rollout.verdict", detail)
            if not ok:
                gate_span.set_status("ERROR")
            gate_span.end()
        return ok, detail

    vocab = getattr(getattr(candidate, "cfg", None), "vocab_size", None)
    for n, prompt in enumerate(prompts):
        try:
            req = candidate.submit(GenRequest(
                list(prompt), max_new_tokens=max_new, temperature=0.0,
                eos_token=-1, adapter=adapter, traceparent=tp,
                probe=True,
            ))
            toks = req.tokens(timeout=timeout)
        except Exception as e:  # noqa: BLE001 — a crashing replay IS the verdict
            return _verdict(False, f"shadow probe {n} crashed: {e!r}")
        if len(toks) != max_new:
            return _verdict(
                False,
                f"shadow probe {n} incomplete ({len(toks)}/{max_new} "
                f"tokens, finish={req.finish_reason!r})",
            )
        if vocab is not None and any(t < 0 or t >= vocab for t in toks):
            return _verdict(
                False, f"shadow probe {n} emitted out-of-vocabulary token"
            )
    return _verdict(True, "ok")


class _RolloutBase:
    """Shared bookkeeping for the fleet controller and the single-engine
    swap: state machine, history ring, metrics, the bake-window watch."""

    def __init__(self, *, label: str, metrics=None, logger=None,
                 bake_s: float | None = None,
                 shadow_probes: int | None = None,
                 drain_timeout_s: float | None = None,
                 interval_s: float = 0.05):
        self.label = label
        self.metrics = metrics
        self.logger = logger
        self.bake_s = (
            _env_float("TPU_LLM_ROLLOUT_BAKE_S", 5.0)
            if bake_s is None else max(0.0, float(bake_s))
        )
        self.shadow_probes = (
            _env_int("TPU_LLM_ROLLOUT_SHADOW", 2)
            if shadow_probes is None else max(0, int(shadow_probes))
        )
        self.drain_timeout_s = (
            _env_float("TPU_LLM_ROLLOUT_DRAIN_S", 120.0)
            if drain_timeout_s is None else max(0.1, float(drain_timeout_s))
        )
        self.interval = interval_s
        self.state = "idle"
        self.error: str | None = None
        self.started_at: float | None = None
        self.finished_at: float | None = None
        self.canary_fails = 0
        self.shadow_fails = 0
        self._history: list[str] = []
        self._stop = False
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self.started_at = time.perf_counter()
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_rollouts_started_total", model=self.label
            )
        self._thread = threading.Thread(
            target=self._run_safe, name="llm-rollout", daemon=True
        )
        self._thread.start()

    def _run_safe(self) -> None:
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — a crashed controller must land terminal
            self.error = self.error or f"rollout controller crashed: {e!r}"
            if self.logger is not None:
                self.logger.error(f"rollout controller crashed: {e!r}")
            try:
                self._converge_after_crash()
            finally:
                if self.state in _ACTIVE_STATES:
                    self._finish("aborted")

    def _run(self) -> None:  # pragma: no cover — subclass responsibility
        raise NotImplementedError

    def _converge_after_crash(self) -> None:
        """Best-effort single-version convergence after an unexpected
        controller exception. Subclasses override."""

    def active(self) -> bool:
        return self.state in _ACTIVE_STATES

    def close(self, timeout: float = 10.0) -> None:
        self._stop = True
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)

    def wait(self, timeout: float = 120.0) -> str:
        """Block until the rollout reaches a terminal state (tests and
        scripts). Returns the final state."""
        deadline = time.monotonic() + timeout
        while self.active() and time.monotonic() < deadline:
            time.sleep(0.01)
        return self.state

    # -- state + visibility -----------------------------------------------
    def _note(self, event: str) -> None:
        self._history.append(f"{time.strftime('%H:%M:%S')} {event}")
        del self._history[:-32]  # bounded debug ring
        if self.logger is not None:
            self.logger.info(f"rollout[{self.label}]: {event}")

    def _set_state(self, state: str) -> None:
        self.state = state
        self._note(f"state -> {state}")
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_rollout_state", ROLLOUT_STATE_GAUGE[state],
                model=self.label,
            )

    def _finish(self, state: str) -> None:
        self.finished_at = time.perf_counter()
        self._set_state(state)
        if self.metrics is not None and state in ("completed", "rolled_back"):
            self.metrics.increment_counter(
                f"app_llm_rollouts_{state}_total", model=self.label
            )

    def snapshot(self) -> dict:
        out = {
            "state": self.state,
            "from_version": self.from_version,
            "to_version": self.to_version,
            "bake_s": self.bake_s,
            "shadow_probes": self.shadow_probes,
            "canary_fails": self.canary_fails,
            "shadow_fails": self.shadow_fails,
            "error": self.error,
            "history": list(self._history),
        }
        if self.started_at is not None:
            end = self.finished_at or time.perf_counter()
            out["elapsed_s"] = round(end - self.started_at, 2)
        return out

    # -- shared mechanics -------------------------------------------------
    def _injector(self):
        from .faults import default_injector

        inj = getattr(self, "_fault_injector", None)
        return inj if inj is not None else default_injector()

    def _count_fault(self, point: str) -> None:
        if self.logger is not None:
            self.logger.warn(f"fault injection: {point} fired on {self.label}")
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_faults_injected_total", point=point, model=self.label,
            )

    def _wait_drained(self, engine, deadline: float) -> bool:
        while not self._stop and time.perf_counter() < deadline:
            if not engine.alive() or engine.drained():
                return True
            time.sleep(self.interval)
        return not engine.alive() or engine.drained()

    def _bake_watch(self, engines_fn, errored_baseline: int,
                    quarantine_baseline: int) -> str | None:
        """Watch the post-shift fleet for ``bake_s`` seconds. Returns a
        regression reason, or None when the bake window passed clean.
        The signals are exactly the ones the resilience stack already
        classifies: a replica death (step fault, watchdog hang,
        numerical trip — all land as ``alive() == False`` within a poll
        interval and are billed by the PR 7 ledger), a device
        quarantine, a request finishing ``error``/``poison``, and the
        deterministic ``rollout_bake_regression`` fault point."""
        t_end = time.perf_counter() + self.bake_s
        while not self._stop and time.perf_counter() < t_end:
            if self._injector().take("rollout_bake_regression", self.label):
                self._count_fault("rollout_bake_regression")
                return "injected rollout_bake_regression"
            engines = engines_fn()
            dead = [e for e in engines if not e.alive()]
            if dead:
                why = getattr(dead[0], "died_reason", None) or "unknown"
                return f"replica death during bake ({why})"
            errored = sum(e.errored for e in engines)
            if errored > errored_baseline:
                return (
                    f"request errors during bake "
                    f"(+{errored - errored_baseline})"
                )
            q = getattr(self, "_quarantines_fn", None)
            if q is not None and q() > quarantine_baseline:
                return "device quarantine during bake"
            time.sleep(self.interval)
        return None


class RolloutController(_RolloutBase):
    """Blue-green replica shift over a ``ReplicatedLLMEngine``.

    The fleet owns the versioned weight registry
    (``fleet._versions[version] = (cfg, params)``, staged by
    ``fleet.deploy``) and the build/canary seams; the controller owns
    the WHEN and the guarantee: one replica out of routing at a time,
    in-flight work finished on the old weights, every candidate gated
    before admission, and a fleet that ends fully on one version no
    matter which step failed."""

    def __init__(self, fleet, to_version: str, **kw):
        super().__init__(
            label=fleet.label, metrics=fleet.metrics,
            logger=fleet.logger, **kw,
        )
        self.fleet = fleet
        self.from_version = fleet.version
        self.to_version = to_version
        self.shifted = 0
        self.total = len(fleet.engines)
        self._fault_injector = fleet._engine_kw.get("fault_injector")
        self._quarantines_fn = lambda: fleet.health.quarantines

    def snapshot(self) -> dict:
        out = super().snapshot()
        out["shifted"] = self.shifted
        out["total"] = self.total
        return out

    # -- main sequence ----------------------------------------------------
    def _run(self) -> None:
        fleet = self.fleet
        self._set_state("shifting")
        for i in range(len(fleet.engines)):
            if self._stop or fleet._draining:
                self._finish("aborted")
                return
            if not self._shift_slot(i):
                self._rollback()
                return
            self.shifted += 1
        quarantine_base = fleet.health.quarantines
        errored_base = sum(e.errored for e in fleet.engines)
        self._set_state("baking")
        regression = self._bake_watch(
            lambda: list(fleet.engines), errored_base, quarantine_base
        )
        if self._stop or fleet._draining:
            self._finish("aborted")
            return
        if regression is not None:
            self.error = regression
            self._note(f"bake regression: {regression}")
            self._rollback()
            return
        # committed: the staged version is THE version; other retained
        # params are dropped (host memory) and their canary refs pruned
        fleet.version = self.to_version
        for v in list(fleet._versions):
            if v != self.to_version:
                fleet._versions.pop(v, None)
                fleet._canary_ref.pop(v, None)
        fleet._observe_versions()
        self._finish("completed")

    def _shift_slot(self, i: int) -> bool:
        """Move replica slot i to the staged version. True on success;
        False leaves the fleet mid-shift for _rollback to converge.

        The hold is released only on SUCCESS: a failed shift leaves the
        slot deliberately dead until _rollback rebuilds it, and
        releasing the hold in between would let the supervisor both
        bill the deliberate close to the device health ledger (a
        quarantine for a failure that never happened) and race
        _rollback's rebuild of the same slot. _rollback clears every
        hold when it finishes."""
        fleet = self.fleet
        fleet._rollout_hold.add(i)
        old = fleet.engines[i]
        if old.alive():
            # per-replica drain: the router stops feeding this
            # replica (accepting() is False) while its in-flight
            # requests FINISH ON THE OLD WEIGHTS — nothing is
            # dropped and no stream changes version mid-flight
            old.drain()
            if not self._wait_drained(
                old, time.perf_counter() + self.drain_timeout_s
            ):
                if self._stop:
                    return False
                # wedged in-flight work: put the replica back in
                # service rather than killing live streams
                old.undrain()
                self.error = (
                    f"slot {i} failed to drain within "
                    f"{self.drain_timeout_s:.0f}s"
                )
                return False
            old.close()
        picked = fleet._spec_for_rebuild(i)
        if picked is None:
            self.error = f"slot {i}: no usable device for rebuild"
            return False
        spec, key = picked
        try:
            cand = fleet._build_replica(
                i, spec=spec, version=self.to_version
            )
        except Exception as e:  # noqa: BLE001 — a failed build rolls back
            self.error = f"slot {i} build on {key} failed: {e!r}"
            return False
        ok, detail = self._gate(cand)
        if not ok:
            try:
                cand.close()
            except Exception:  # noqa: BLE001 — teardown must not mask the verdict
                pass
            self.error = f"slot {i} rejected: {detail}"
            return False
        if self._stop or fleet._draining:
            cand.close()
            return False
        fleet.engines[i] = cand  # atomic item swap: routers see old or new
        fleet._current_keys[i] = key
        fleet._slot_versions[i] = self.to_version
        fleet.health.probe_ok(key)
        fleet._observe_versions()
        self._note(f"slot {i} shifted to {self.to_version} on {key}")
        fleet._rollout_hold.discard(i)
        return True

    def _gate(self, candidate) -> tuple[bool, str]:
        """Canary probe + shadow-traffic replay + the deterministic
        ``rollout_canary_fail`` fault point. A candidate that fails any
        of them never receives live traffic."""
        fleet = self.fleet
        if self._injector().take("rollout_canary_fail", fleet.label):
            self._count_fault("rollout_canary_fail")
            self.canary_fails += 1
            return False, "injected rollout_canary_fail"
        ok, detail = fleet._canary_check(candidate)
        if not ok:
            self.canary_fails += 1
            return False, f"canary: {detail}"
        if self.shadow_probes > 0:
            # most recent distinct real prompts, bounded
            seen: list[tuple] = []
            for p in reversed(list(fleet._shadow_ring)):
                if p not in seen:
                    seen.append(p)
                if len(seen) >= self.shadow_probes:
                    break
            if seen:
                ok, detail = shadow_probe(candidate, seen)
                if not ok:
                    self.shadow_fails += 1
                    return False, detail
        return True, "ok"

    # -- rollback ---------------------------------------------------------
    def _rollback(self) -> None:
        """Converge every slot back onto the retained old version. Slots
        whose rebuild fails are left pointed at the old version for the
        supervisor to converge (its _build_replica default is the
        slot's recorded version) — the fleet NEVER ends wedged with two
        versions in routing."""
        fleet = self.fleet
        self._set_state("rolling_back")
        # incident seam (gofr_tpu.flightrec): a rollback means the new
        # version FAILED in production — capture the fleet (bake-window
        # counters, canary verdicts, per-version requests) before the
        # converge below rebuilds the evidence away
        incident = getattr(fleet, "incident", None)
        if incident is not None:
            incident(
                "rollback",
                reason=f"rolling back {self.to_version} -> "
                       f"{self.from_version}: {self.error or 'gate failed'}",
            )
        try:
            for i in range(len(fleet.engines)):
                if self._stop or fleet._draining:
                    self._finish("aborted")
                    return
                eng = fleet.engines[i]
                if eng.alive() and eng.version == self.from_version:
                    continue
                fleet._rollout_hold.add(i)
                # record intent FIRST: even if this rebuild fails, the
                # supervisor's next rebuild of the slot uses from_version
                fleet._slot_versions[i] = self.from_version
                if eng.alive():
                    eng.drain()
                    if not self._wait_drained(
                        eng, time.perf_counter() + self.drain_timeout_s
                    ):
                        # rollback must CONVERGE (a wedged new-version
                        # replica cannot block it forever), but its
                        # in-flight requests deserve the failover rescue
                        # a crash would get — _die hands them to the
                        # router (same-version pin applies), where
                        # close() would silently cancel them
                        eng._die(
                            "rollout rollback: replica failed to drain "
                            f"within {self.drain_timeout_s:.0f}s"
                        )
                    eng.close()
                picked = fleet._spec_for_rebuild(i)
                if picked is None:
                    self._note(f"rollback: slot {i} parked (no device)")
                    continue
                spec, key = picked
                try:
                    repl = fleet._build_replica(
                        i, spec=spec, version=self.from_version
                    )
                except Exception as e:  # noqa: BLE001 — supervisor converges later
                    self._note(f"rollback: slot {i} rebuild failed: {e!r}")
                    continue
                ok, detail = fleet._canary_check(repl)
                if not ok:
                    self._note(f"rollback: slot {i} canary: {detail}")
                    try:
                        repl.close()
                    except Exception:  # noqa: BLE001
                        pass
                    continue
                fleet.engines[i] = repl
                fleet._current_keys[i] = key
                fleet._observe_versions()
                self._note(f"slot {i} rolled back to {self.from_version}")
                fleet._rollout_hold.discard(i)
            # drop the rejected version entirely: params freed, canary
            # refs pruned, and a later deploy may reuse the label after
            # fixing it
            fleet._versions.pop(self.to_version, None)
            fleet._canary_ref.pop(self.to_version, None)
            fleet._observe_versions()
            self._finish("rolled_back")
        finally:
            # every hold this controller still owns (kept across a
            # failed shift, a failed rollback rebuild, or an abort) is
            # released in one place: slots whose rebuild failed stay
            # recorded on from_version, so the supervisor converges
            # them on the OLD weights
            fleet._rollout_hold.clear()

    def _converge_after_crash(self) -> None:
        if any(v != self.from_version for v in self.fleet._slot_versions):
            self._rollback()


class _EngineSwapRollout(_RolloutBase):
    """Blue-green swap for a bare single engine: build the staged
    version NEXT TO the serving engine (two weight copies resident for
    the duration — the price of zero downtime without a second
    replica), gate it, repoint the handle, drain the old engine, and
    keep it alive through the bake window so a regression swaps back
    instead of rebuilding."""

    def __init__(self, handle, to_version: str, cfg, params, **kw):
        super().__init__(
            label=handle._engine.label, metrics=handle._metrics,
            logger=handle._logger, **kw,
        )
        self.handle = handle
        self.from_version = handle._engine.version
        self.to_version = to_version
        self._cfg, self._params = cfg, params
        self._fault_injector = handle._build_kw.get("fault_injector")

    def _run(self) -> None:
        from ..llm import LLMEngine

        handle = self.handle
        old = handle._engine
        self._set_state("shifting")
        try:
            cand = LLMEngine(
                self._cfg, self._params,
                version=self.to_version, **handle._build_kw,
            )
        except Exception as e:  # noqa: BLE001 — staged build failed; old keeps serving
            self.error = f"build failed: {e!r}"
            self._finish("rolled_back")
            return
        # re-stage registered adapters BEFORE the gate (gofr_tpu.lora):
        # the candidate must serve the same tenant set as the engine it
        # replaces, and a failed re-stage is a gate failure — swapping
        # in an engine that 404s every tenant is a regression
        if getattr(cand, "lora_slots", 0):
            for aname, rec in list(handle._adapters_host.items()):
                try:
                    cand.load_adapter(
                        aname, rec["adapter"], version=rec["version"],
                        alpha=rec["alpha"], fair_weight=rec["fair_weight"],
                    )
                except Exception as e:  # noqa: BLE001
                    self.error = f"adapter {aname!r} re-stage failed: {e!r}"
                    try:
                        cand.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._finish("rolled_back")
                    return
        ok, detail = self._gate(cand)
        if not ok:
            self.error = detail
            try:
                cand.close()
            except Exception:  # noqa: BLE001
                pass
            self._finish("rolled_back")
            return
        if self._stop:
            cand.close()
            self._finish("aborted")
            return
        # atomic repoint: new submissions land on the staged engine;
        # in-flight requests finish on the old weights behind the drain
        handle._engine = cand
        old.drain()
        self._note(f"swapped to {self.to_version}; old engine draining")
        errored_base = cand.errored
        self._set_state("baking")
        regression = self._bake_watch(lambda: [cand], errored_base, 0)
        if self._stop:
            # teardown raced the bake: the staged engine is the serving
            # one — retire the drained old engine instead of leaking its
            # threads and device-resident weights
            old.close()
            self._finish("aborted")
            return
        if regression is not None:
            self.error = regression
            # swap BACK: the old engine is still alive and warm — reopen
            # its admission and retire the regressed candidate
            handle._engine = old
            old.undrain()
            cand.drain()
            if not self._wait_drained(
                cand, time.perf_counter() + self.drain_timeout_s
            ):
                # a bare engine has no failover to rescue into: bounded
                # convergence wins over waiting forever, and the close
                # is visible here and in the snapshot history
                self._note(
                    "regressed engine failed to drain; closing with "
                    "in-flight work"
                )
            cand.close()
            self._note(f"bake regression ({regression}); swapped back")
            self._finish("rolled_back")
            return
        # committed: retire the old engine once its in-flight work ends
        if not self._wait_drained(
            old, time.perf_counter() + self.drain_timeout_s
        ):
            self._note(
                "old engine failed to drain; closing with in-flight work"
            )
        old.close()
        handle._cfg, handle._params = self._cfg, self._params
        self._finish("completed")

    def _gate(self, candidate) -> tuple[bool, str]:
        from .health import canary_check

        if self._injector().take("rollout_canary_fail", self.label):
            self._count_fault("rollout_canary_fail")
            self.canary_fails += 1
            return False, "injected rollout_canary_fail"
        # no same-version peer exists by construction: completeness +
        # vocabulary judgment (the no-reference canary path)
        ok, detail, _toks = canary_check(candidate)
        if not ok:
            self.canary_fails += 1
            return False, f"canary: {detail}"
        if self.shadow_probes > 0:
            seen: list[tuple] = []
            for p in reversed(list(self.handle._shadow_ring)):
                if p not in seen:
                    seen.append(p)
                if len(seen) >= self.shadow_probes:
                    break
            if seen:
                ok, detail = shadow_probe(candidate, seen)
                if not ok:
                    self.shadow_fails += 1
                    return False, detail
        return True, "ok"


class ModelHandle:
    """Versioned registry entry for one registered LLM — what
    ``register_llm`` returns and ``ctx.tpu().llm(name)`` resolves.

    Everything callers did with the raw engine keeps working: the
    handle proxies attribute access to the live engine (submit,
    generate, stats, debug_state, drain, stream consumption, replica
    internals). On top it adds the model lifecycle:

    - ``deploy(cfg, params, version=...)`` stages a new weight version
      and shifts traffic with zero downtime (fleet: per-replica
      blue-green via :class:`RolloutController`; bare engine:
      build-gate-swap via the engine-swap rollout).
    - ``rollout_state()`` / ``version`` for the admin route and
      debug views.
    """

    def __init__(self, name: str, engine, *, cfg, params,
                 build_kw: dict | None = None, logger=None, metrics=None):
        self.name = name
        self._engine = engine
        self._cfg = cfg
        self._params = params
        self._build_kw = dict(build_kw or {})
        self._logger = logger
        self._metrics = metrics
        self._lock = threading.Lock()
        self._swap: _EngineSwapRollout | None = None
        # single-engine shadow source (the fleet keeps its own ring)
        self._shadow_ring: list = []
        # single-engine adapter registry (gofr_tpu.lora): host copies of
        # registered adapters so a blue-green engine swap re-stages them
        # into the candidate (the fleet keeps its own _adapters_host)
        self._adapters_host: dict = {}

    # -- engine surface ----------------------------------------------------
    @property
    def engine(self):
        return self._engine

    @property
    def cfg(self):
        """The ACTIVE version's config (a fleet retains one per version;
        a bare engine carries its own)."""
        eng = self._engine
        if hasattr(eng, "_versions"):
            return eng._versions[eng.version][0]
        return eng.cfg

    def __getattr__(self, item):
        # only consulted when the handle itself lacks the attribute:
        # the full engine surface flows through unchanged
        return getattr(self._engine, item)

    def submit(self, req):
        eng = self._engine
        out = eng.submit(req)
        if not hasattr(eng, "_shadow_ring"):  # bare engine: handle-kept ring
            self._shadow_ring.append(tuple(req.prompt_tokens[:32]))
            del self._shadow_ring[:-8]
        return out

    def generate(self, prompt_tokens, **kw):
        from ..llm import GenRequest

        return self.submit(GenRequest(list(prompt_tokens), **kw)).tokens()

    # -- model lifecycle ---------------------------------------------------
    def deploy(self, cfg=None, params=None, *, version: str | None = None,
               bake_s: float | None = None,
               shadow_probes: int | None = None,
               drain_timeout_s: float | None = None) -> dict:
        """Stage new weights and shift traffic onto them with zero
        downtime; see RolloutController / _EngineSwapRollout for the
        two execution shapes. Validates the param tree against the
        config BEFORE any device transfer (a bad checkpoint is a 4xx,
        never a dead replica) and returns the rollout snapshot
        immediately — progress is visible in ``rollout_state()``."""
        eng = self._engine
        if hasattr(eng, "deploy"):  # replicated fleet: its own controller
            return eng.deploy(
                cfg, params, version=version, bake_s=bake_s,
                shadow_probes=shadow_probes, drain_timeout_s=drain_timeout_s,
            )
        from ..models.checkpoint import validate_params

        if params is None:
            raise RolloutError("deploy() needs params (the new weights)")
        cfg = self._cfg if cfg is None else cfg
        validate_params(params, cfg)
        with self._lock:
            if self._swap is not None and self._swap.active():
                raise RolloutInProgress(
                    f"rollout to {self._swap.to_version!r} already in "
                    f"progress (state {self._swap.state})"
                )
            if version is None:
                version = _next_version(eng.version)
            if version == eng.version:
                raise RolloutError(
                    f"model version {version!r} is already active"
                )
            self._swap = _EngineSwapRollout(
                self, version, cfg, params, bake_s=bake_s,
                shadow_probes=shadow_probes, drain_timeout_s=drain_timeout_s,
            )
            self._swap.start()
        return self._swap.snapshot()

    # -- multi-tenant adapters (gofr_tpu.lora;
    # docs/advanced-guide/multi-tenancy.md) --------------------------------
    def register_adapter(
        self, name: str, adapter: dict, *, version: str = "v1",
        alpha: float | None = None, fair_weight: float | None = None,
        shadow_probes: int | None = None, quota: float | None = None,
    ) -> dict:
        """Canary-gated adapter hot-load — the PR 9 deploy shape scaled
        down to a table row. The checkpoint is validated against the
        base config (``lora.validate_adapter`` via the engine's
        ``eval_shape``-derived dims; a bad shape is a ValueError/4xx,
        never a corrupted table), staged under ``<name>@<version>``,
        shadow-gated with real recent prompts replayed THROUGH the
        staged delta on the live engine, and only then atomically
        published under ``name``. On a gate reject the staging row is
        evicted and the previous binding of ``name`` — if any — keeps
        serving untouched (canary-reject-keeps-serving, test-pinned).
        In-flight requests on a replaced binding drain on their old gid.
        ``fair_weight`` sets the tenant's FairLedger share
        (``adapter:<name>``) after publish; ``quota`` sets a hard
        token-rate ceiling (tok/s) on the same tenant id, enforced at
        admission against the goodput usage meter
        (docs/advanced-guide/cost-accounting.md)."""
        eng = self._engine
        staging = f"{name}@{version}"
        probes = (
            _env_int("TPU_LLM_ADAPTER_SHADOW", 2)
            if shadow_probes is None else max(0, int(shadow_probes))
        )
        eng.load_adapter(staging, adapter, version=version, alpha=alpha)
        ring = getattr(eng, "_shadow_ring", None)
        if ring is None:  # bare engine: the handle keeps the ring
            ring = self._shadow_ring
        seen: list[tuple] = []
        for p in reversed(list(ring)):
            if p not in seen:
                seen.append(p)
            if len(seen) >= probes:
                break
        if probes > 0 and seen:
            ok, detail = shadow_probe(eng, seen, adapter=staging)
            if not ok:
                eng.evict_adapter(staging)
                host = getattr(eng, "_adapters_host", None)
                if host is not None:
                    host.pop(staging, None)
                if self._metrics is not None:
                    self._metrics.increment_counter(
                        "app_llm_rollouts_rolled_back_total",
                        model=getattr(eng, "label", self.name),
                    )
                raise RolloutError(
                    f"adapter {name!r} version {version!r} rejected by "
                    f"shadow gate: {detail}"
                )
        eng.publish_adapter(staging, name)
        if fair_weight is not None:
            ledger = getattr(eng, "ledger", None)
            if ledger is not None:
                ledger.set_weight(f"adapter:{name}", fair_weight)
        if quota is not None:
            set_q = getattr(eng, "set_tenant_quota", None)
            if set_q is not None:
                set_q(f"adapter:{name}", float(quota))
        # host registry: the fleet keeps its own (replica rebuilds
        # re-stage from it); a bare engine's lives on this handle so the
        # blue-green engine swap can re-stage into its candidate
        rec = {
            "adapter": adapter, "version": str(version), "alpha": alpha,
            "fair_weight": fair_weight, "quota": quota,
        }
        host = getattr(eng, "_adapters_host", None)
        if host is not None:
            host.pop(staging, None)
            host[name] = rec
        else:
            self._adapters_host[name] = rec
        return {"name": name, "version": version, "state": "published"}

    def retire_adapter(self, name: str) -> None:
        """Unbind ``name`` everywhere (idle gids free now, busy ones
        drain as zombies) and forget its host copy — a later engine
        swap or replica rebuild will not resurrect it."""
        eng = self._engine
        self._adapters_host.pop(name, None)
        host = getattr(eng, "_adapters_host", None)
        if host is not None:
            host.pop(name, None)
        eng.evict_adapter(name)

    def rollout_state(self) -> dict | None:
        eng = self._engine
        if hasattr(eng, "rollout_state"):
            return eng.rollout_state()
        return None if self._swap is None else self._swap.snapshot()

    def close(self) -> None:
        if self._swap is not None:
            self._swap.close()
        self._engine.close()


def _next_version(current: str) -> str:
    """v3 -> v4; anything unconventional gets a ``.next`` suffix rather
    than a guessed number."""
    import re

    m = re.match(r"^v(\d+)$", current)
    return f"v{int(m.group(1)) + 1}" if m else f"{current}.next"
