"""Device health: failure ledger, quarantine state machine, canary gate.

PR 5 gave the fleet a supervisor that rebuilds a dead replica — on the
SAME device, forever, under capped backoff. That closes the loop for
transient faults (an XLA abort, a watchdog-killed hang) but inverts it
for a persistently sick chip: an HBM bank throwing ECC errors, a wedged
ICI link, a driver fault that survives process restarts. There the
rebuild loop never converges; the fleet silently runs one replica short
while the supervisor burns a core re-warming executables that die on
first dispatch. This module is the missing judgment layer, mirroring the
reference repo's circuit breaker (trip, isolate, probe, reintegrate) at
the TPU-device level:

- :class:`DeviceHealthLedger` — a per-device sliding-window failure
  ledger. Replica deaths and rebuild failures are CLASSIFIED
  (``step_fault`` / ``watchdog_hang`` / ``numerical`` /
  ``rebuild_failure``) and recorded against the device the engine ran
  on; ``TPU_LLM_DEVICE_QUARANTINE_FAILURES`` attributable failures
  inside ``TPU_LLM_DEVICE_QUARANTINE_WINDOW_S`` trip the device into
  QUARANTINE. A quarantined device serves nothing until its cooldown
  (``TPU_LLM_DEVICE_COOLDOWN_S``, doubling per re-trip, capped) elapses
  — it then enters PROBATION: the next rebuild may use it, but only
  behind the canary gate, and the outcome reintegrates the device or
  re-quarantines it with a longer cooldown.
- :func:`canary_check` — the gate itself: a fixed greedy probe prompt
  run on a candidate engine BEFORE it enters routing. When reference
  tokens from a healthy replica exist the candidate must match them
  token-for-token (greedy decode is deterministic, so divergence means
  broken compute, not randomness); without a reference the stream must
  still be complete and in-vocabulary (the numerical-watchdog sentinel
  ``-1`` is out-of-vocabulary by construction, so NaN logits fail here
  too). A half-sick rebuild never receives live traffic.

The supervisor (supervisor.py) drives both; ``ReplicatedLLMEngine``
owns the ledger and exposes it in ``debug_state()["health"]``. The
ledger takes a ``now_fn`` so tier-1 tests drive the window and cooldown
with faked clocks (the overload.py convention).
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "CANARY_MAX_NEW",
    "CANARY_PROMPT",
    "DeviceHealthLedger",
    "canary_check",
    "device_key",
    "spec_device_key",
    "split_device_key",
]

# Failure classes the ledger tallies. Everything a replica death can be
# attributed to maps onto one of these (classify()); "unknown" covers a
# thread that died without a recorded reason — still a death, still
# counted (a sick device does not owe us a tidy stack trace).
FAILURE_REASONS = (
    "step_fault",
    "watchdog_hang",
    "numerical",
    "rebuild_failure",
    "unknown",
)

# Fixed greedy probe: short enough to cost one prefill chunk + one
# decode chunk, long enough that a divergent matmul cannot stay hidden
# behind a lucky argmax (8 sampled positions over the full vocab).
CANARY_PROMPT = (3, 1, 4, 1, 5, 9, 2, 6)
CANARY_MAX_NEW = 8


def device_key(dev) -> str:
    """Stable string identity for one jax device ("cpu:0", "tpu:3")."""
    return f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"


def split_device_key(key: str) -> list[str]:
    """Member device keys of a (possibly "+"-joined submesh) health key.
    The inverse view of spec_device_key: elastic SUBMESH placement needs
    per-chip occupancy/health sets, while the ledger bills the submesh
    as one unit."""
    return key.split("+")


def spec_device_key(spec: dict) -> str:
    """Identity of the device (or submesh) a replica spec pins to. A
    tensor-parallel submesh is one health unit: its chips fail together
    as far as the replica is concerned (any sick member kills the
    replica), so they quarantine together."""
    dev = spec.get("device")
    if dev is not None:
        return device_key(dev)
    mesh = spec.get("mesh")
    if mesh is not None:
        try:
            devs = list(mesh.devices.flat)
        except AttributeError:  # duck-typed test meshes
            devs = list(getattr(mesh, "devices", []) or [])
        if devs:
            return "+".join(sorted(device_key(d) for d in devs))
    return "default"


class DeviceHealthLedger:
    """Sliding-window failure ledger with quarantine / probation states.

    States per device (:meth:`state`):

    - ``healthy``     — full member of the placement pool.
    - ``quarantined`` — tripped; no placement until cooldown elapses.
    - ``probation``   — cooldown elapsed; placement allowed but ONLY
      behind the canary gate. :meth:`probe_ok` reintegrates (state back
      to healthy, failure window cleared); any recorded failure while
      quarantined/probation re-trips with a doubled (capped) cooldown.

    Thread-safe; all mutation under one lock. Reads used on the
    placement path (:meth:`usable`) are a dict lookup plus a clock
    read."""

    def __init__(
        self,
        *,
        failures: int | None = None,
        window_s: float | None = None,
        cooldown_s: float | None = None,
        cooldown_max_s: float | None = None,
        now_fn=time.monotonic,
        metrics=None,
        model: str = "llm",
        logger=None,
    ):
        if failures is None:
            failures = int(
                os.environ.get("TPU_LLM_DEVICE_QUARANTINE_FAILURES", "3")
            )
        if window_s is None:
            window_s = float(
                os.environ.get("TPU_LLM_DEVICE_QUARANTINE_WINDOW_S", "60")
            )
        if cooldown_s is None:
            cooldown_s = float(
                os.environ.get("TPU_LLM_DEVICE_COOLDOWN_S", "30")
            )
        if cooldown_max_s is None:
            cooldown_max_s = max(cooldown_s, 8 * cooldown_s)
        self.failures_limit = max(1, failures)
        self.window_s = max(0.001, window_s)
        self.cooldown_s = max(0.001, cooldown_s)
        self.cooldown_max_s = cooldown_max_s
        self.now = now_fn
        self.metrics = metrics
        self.model = model
        self.logger = logger
        self.quarantines = 0  # total trips (counter twin)
        self._lock = threading.Lock()
        # incident seam (gofr_tpu.flightrec): ReplicatedLLMEngine points
        # this at a live replica's black-box dump — a quarantine trip is
        # a bundle trigger, and the evidence (which device, which
        # failure mix) must be captured while the fleet still has it.
        # Called OUTSIDE the ledger lock; exceptions are swallowed.
        self.on_quarantine = None
        # per-device: {"events": [(t, reason)], "state": str, "until": t,
        #              "cooldown": s, "trips": n, "by_reason": {r: n}}
        self._devices: dict[str, dict] = {}

    # -- classification ---------------------------------------------------
    @staticmethod
    def classify(died_reason: str | None) -> str:
        """Map an engine's ``died_reason`` onto a ledger failure class.
        The strings are the ones ``LLMEngine._die`` callers use; anything
        unrecognized is a plain step fault (the engine's scheduler or
        collector lost the device mid-dispatch)."""
        if not died_reason:
            return "unknown"
        r = died_reason.lower()
        if r.startswith("step watchdog"):
            return "watchdog_hang"
        if r.startswith("numerical watchdog"):
            return "numerical"
        if "rebuild" in r or "canary" in r or "device_sick" in r:
            return "rebuild_failure"
        return "step_fault"

    # -- recording --------------------------------------------------------
    def record_failure(self, device: str, reason: str, detail: str = "") -> bool:
        """Record one attributable failure against ``device``. Returns
        True when this record newly trips (or re-trips) quarantine."""
        if reason not in FAILURE_REASONS:
            reason = "unknown"
        with self._lock:
            now = self.now()
            d = self._devices.setdefault(
                device,
                {"events": [], "state": "healthy", "until": 0.0,
                 "cooldown": self.cooldown_s, "trips": 0,
                 "by_reason": {}},
            )
            d["by_reason"][reason] = d["by_reason"].get(reason, 0) + 1
            d["events"].append((now, reason))
            lo = now - self.window_s
            d["events"] = [e for e in d["events"] if e[0] >= lo]
            if d["state"] == "quarantined":
                # a failure while quarantined (a failed probe rebuild, a
                # death raced into the ledger late): re-trip with a
                # doubled cooldown — repeated offenders wait longer
                d["cooldown"] = min(d["cooldown"] * 2.0, self.cooldown_max_s)
                d["until"] = now + d["cooldown"]
                d["trips"] += 1
                tripped = True
            elif len(d["events"]) >= self.failures_limit:
                d["state"] = "quarantined"
                d["until"] = now + d["cooldown"]
                d["trips"] += 1
                tripped = True
            else:
                tripped = False
            if tripped:
                self.quarantines += 1
        if tripped:
            if self.logger is not None:
                self.logger.error(
                    f"device {device} quarantined ({reason}: {detail or 'n/a'}; "
                    f"trip {self.quarantines}, cooldown "
                    f"{self._devices[device]['cooldown']:.1f}s)"
                )
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_llm_device_quarantines_total", model=self.model
                )
            if self.on_quarantine is not None:
                try:
                    self.on_quarantine(device, f"{reason}: {detail or 'n/a'}")
                except Exception:  # noqa: BLE001 — incident capture is best-effort
                    pass
        self._observe_gauge()
        return tripped

    def probe_ok(self, device: str) -> None:
        """A canary-gated rebuild on ``device`` passed: reintegrate.
        No-op for a healthy device (the common rebuild path)."""
        reintegrated = False
        with self._lock:
            d = self._devices.get(device)
            if d is not None and d["state"] == "quarantined":
                d["state"] = "healthy"
                d["events"] = []  # a clean probe resets the window
                d["cooldown"] = self.cooldown_s
                reintegrated = True
        if reintegrated and self.logger is not None:
            self.logger.info(f"device {device} reintegrated (canary passed)")
        self._observe_gauge()

    # -- reads ------------------------------------------------------------
    def state(self, device: str) -> str:
        with self._lock:
            return self._state_locked(device)

    def _state_locked(self, device: str) -> str:
        d = self._devices.get(device)
        if d is None or d["state"] == "healthy":
            return "healthy"
        if self.now() >= d["until"]:
            return "probation"  # cooldown served; next rebuild may probe
        return "quarantined"

    def usable(self, device: str) -> bool:
        """May a rebuild target this device? Healthy always; probation
        too (that IS the probe — the canary gate guards the outcome)."""
        return self.state(device) != "quarantined"

    def quarantined_count(self) -> int:
        """Devices currently not healthy (quarantined or awaiting a
        successful probe in probation) — the gauge's definition: a
        probation device has NOT yet proven itself back."""
        with self._lock:
            return sum(
                1 for k in self._devices
                if self._state_locked(k) != "healthy"
            )

    def snapshot(self) -> dict:
        with self._lock:
            now = self.now()
            devices = {}
            for k, d in self._devices.items():
                st = self._state_locked(k)
                row = {
                    "state": st,
                    "recent_failures": len(
                        [e for e in d["events"] if e[0] >= now - self.window_s]
                    ),
                    "trips": d["trips"],
                    "by_reason": dict(d["by_reason"]),
                }
                if st == "quarantined":
                    row["cooldown_remaining_s"] = round(d["until"] - now, 2)
                devices[k] = row
            return {
                "quarantines": self.quarantines,
                "failures_limit": self.failures_limit,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                "devices": devices,
            }

    def _observe_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.set_gauge(
                "app_llm_devices_quarantined",
                float(self.quarantined_count()), model=self.model,
            )


def canary_check(
    candidate,
    reference_tokens: list[int] | None = None,
    *,
    prompt=CANARY_PROMPT,
    max_new: int = CANARY_MAX_NEW,
    timeout: float = 60.0,
) -> tuple[bool, str, list[int]]:
    """Run the fixed greedy probe on ``candidate`` (an LLMEngine that is
    NOT yet routed) and judge the result. Returns ``(ok, detail,
    tokens)`` — detail is a human reason on rejection, tokens are the
    candidate's output (a passing no-reference run becomes the cached
    fleet reference).

    With ``reference_tokens`` (a healthy replica's output for the same
    prompt): exact token equality — greedy decode is deterministic per
    params+config, so any divergence is broken device compute. Without:
    the stream must complete (``max_new`` tokens — the probe sets no
    eos, a short stream means a died/hung engine) and stay inside the
    vocabulary (non-finite logits surface as the numerical-watchdog
    sentinel ``-1``, or as a dead engine)."""
    from ..llm import GenRequest

    try:
        req = candidate.submit(GenRequest(
            list(prompt), max_new_tokens=max_new, temperature=0.0,
            eos_token=-1, probe=True,
        ))
        toks = req.tokens(timeout=timeout)
    except Exception as e:  # noqa: BLE001 — a crashing probe IS the verdict
        return False, f"probe crashed: {e!r}", []
    if len(toks) != max_new:
        return (
            False,
            f"probe stream incomplete ({len(toks)}/{max_new} tokens, "
            f"finish={req.finish_reason!r})",
            toks,
        )
    vocab = getattr(getattr(candidate, "cfg", None), "vocab_size", None)
    if vocab is not None and any(t < 0 or t >= vocab for t in toks):
        return False, f"probe emitted out-of-vocabulary token: {toks}", toks
    if reference_tokens is not None and toks != list(reference_tokens):
        return (
            False,
            f"probe diverged from healthy reference: {toks} != "
            f"{list(reference_tokens)}",
            toks,
        )
    return True, "ok", toks
