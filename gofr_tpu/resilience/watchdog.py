"""Step watchdog: convert a hung device step into a detectable death.

``LLMEngine._die`` fires when an engine THREAD exits — but a hung XLA
execution or wedged device->host transfer never exits; it blocks the
scheduler or collector inside a C call forever. Before this module a hang
was invisible: ``alive()`` stayed True, the replica router kept feeding
the corpse, and every routed consumer blocked until its stream timeout.

The fix is heartbeats plus a monitor. Each engine thread wraps its
blocking device interaction in a :class:`Heartbeat` beat (dispatch on the
scheduler, fetch on the collector); the :class:`StepWatchdog` thread
samples both beats and, when one has been in flight longer than the
threshold (``TPU_LLM_STEP_WATCHDOG_S``), trips: counts
``app_llm_watchdog_trips_total``, then drives the engine's ``_die`` with
a distinct reason so the failover hook rescues the in-flight requests and
the supervisor schedules a replacement replica.

The die path must tolerate a WEDGED ENGINE LOCK: a hang inside a
dispatch happens under the scheduler's critical section, so the watchdog
passes a lock acquisition timeout — if the lock cannot be had, the
engine is still marked dead (router stops feeding it) and the stuck
thread is abandoned (Python cannot kill a thread blocked in C; the
supervisor replaces the whole replica instead).

Compile stalls are deliberately NOT covered: beats wrap serving
dispatch/fetch only, never ``_warm`` — a cold compile can legitimately
take minutes and must not trip a seconds-scale watchdog.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

__all__ = ["Heartbeat", "StepWatchdog"]


class Heartbeat:
    """One thread's in-flight device operation: (name, started-at).

    Written by the engine thread, read by the watchdog — both touch two
    slots without a lock, which is safe by ordering: ``begin`` publishes
    the timestamp BEFORE the name, ``end`` retracts the name first, and
    the reader starts from the name. A torn read costs one stale sample
    at the next interval, never a false trip."""

    __slots__ = ("_name", "_t0")

    def __init__(self):
        self._name: str | None = None
        self._t0 = 0.0

    def begin(self, name: str) -> None:
        self._t0 = time.perf_counter()
        self._name = name

    def end(self) -> None:
        self._name = None

    @contextmanager
    def beat(self, name: str):
        self.begin(name)
        try:
            yield
        finally:
            self.end()

    def stalled(self) -> tuple[str | None, float]:
        """(operation name, seconds in flight) — (None, 0.0) when idle."""
        name = self._name
        if name is None:
            return None, 0.0
        return name, time.perf_counter() - self._t0


class StepWatchdog:
    """Per-engine monitor thread over a set of heartbeats.

    ``threshold_s`` is the step budget; the sampling interval is
    threshold/4 capped at 1 s, so a hang is converted into a death
    within threshold + one interval (the acceptance bound). One-shot:
    after a trip the engine is dead and the thread exits."""

    def __init__(
        self,
        engine,
        threshold_s: float,
        *,
        interval_s: float | None = None,
    ):
        self.engine = engine
        self.threshold = float(threshold_s)
        self.interval = (
            interval_s if interval_s is not None
            else max(0.01, min(self.threshold / 4.0, 1.0))
        )
        self.trips = 0
        self._thread = threading.Thread(
            target=self._run, name="llm-engine-watchdog", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        eng = self.engine
        while not eng._stop:
            for hb in (eng._hb_dispatch, eng._hb_fetch):
                name, dt = hb.stalled()
                if name is not None and dt > self.threshold:
                    self._trip(name, dt)
                    return
            time.sleep(self.interval)

    def _trip(self, name: str, dt: float) -> None:
        eng = self.engine
        self.trips += 1
        if eng.metrics is not None:
            eng.metrics.increment_counter(
                "app_llm_watchdog_trips_total", model=eng.label
            )
        if eng.logger is not None:
            eng.logger.error(
                f"LLM engine watchdog: {name} in flight {dt:.1f}s "
                f"(threshold {self.threshold:.1f}s) — killing replica"
            )
        # The hung call may hold the engine lock (dispatch section): a
        # bounded acquisition lets _die degrade to mark-dead-only instead
        # of deadlocking the watchdog thread on the wedged lock.
        eng._die(
            f"step watchdog: {name} exceeded {self.threshold:.1f}s",
            lock_timeout=min(5.0, max(1.0, self.threshold)),
        )

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout=timeout)
