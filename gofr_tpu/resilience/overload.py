"""Overload control: fair queuing ledger, retry budget, brownout controller.

PR 5 made the fleet survive crashes; this module makes it survive
DEMAND — sustained load above capacity, where the failure mode is not a
dead replica but a greedy client starving everyone else, a batch job
squeezing interactive traffic out of its latency budget, and blind 429s
that teach clients to hammer the retry button. Three small, clock-
injectable pieces (gofr_tpu.llm wires them through the scheduler and
the replica router; docs/advanced-guide/overload.md has the model):

- :class:`FairLedger` — per-client virtual token counters ("Fairness in
  Serving Large Language Models", OSDI'24): every served token is billed
  to its client at ``tokens / weight``, and the engine orders its
  waiting queue by least-billed-first instead of FIFO, so a flood from
  one client cannot push another below its weighted share. One ledger is
  shared across all replicas of a fleet (ReplicatedLLMEngine), making
  fairness a fleet property rather than a per-engine accident.
- :class:`RetryBudget` — a token bucket bounding router-side retries
  (failover re-dispatch and mid-submit replica death). Under overload,
  unbounded retries amplify offered load exactly when capacity is
  scarcest — the retry-storm pathology the inter-service circuit breaker
  (gofr_tpu.service) guards against, reproduced inside the fleet.
- :class:`OverloadController` — the degrade-then-shed state machine:
  predicted queue wait (queued tokens / measured throughput) above the
  brownout threshold for a sustained hold engages BROWNOUT (new
  batch-class requests get their ``max_new_tokens`` clamped — shorter
  answers, not errors); predicted wait above the shed threshold sheds
  with a computed Retry-After. Degrade, then shed, never collapse.

Everything takes a ``now_fn`` so tier-1 tests drive the state machines
with faked clocks; the ``overload_pressure`` fault point (faults.py)
injects deterministic pressure through a black-box process.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

__all__ = ["FairLedger", "OverloadController", "RetryBudget"]


class FairLedger:
    """Per-client weighted virtual token counters (the VTC scheduler's
    ledger). ``charge(client, tokens)`` bills served work at
    ``tokens / weight``; the engine sorts its waiting queue by
    :meth:`counter` ascending, so the least-served client (in weighted
    terms) is admitted first.

    New-arrival rule: a client absent from the ledger (or idle long
    enough to be evicted) starts at the MINIMUM counter among clients
    with work currently waiting — an idle period must not bank unbounded
    credit, and a flood cannot be beaten by reconnecting under a fresh
    name with zero debt. ``touch()`` applies the same lift to a known
    client returning from idle.

    Bounded: at most ``max_clients`` entries, least-debt-evicted (NOT
    LRU: LRU would let a flooder spray spoofed ids to evict its own
    heavy counter and re-enter with laundered debt) — the ledger is an
    ordering heuristic, not an account book, and an evicted client
    simply re-enters under the new-arrival rule.
    """

    def __init__(
        self,
        weights: dict[str, float] | None = None,
        *,
        default_weight: float = 1.0,
        max_clients: int = 1024,
    ):
        self._lock = threading.Lock()
        self._weights = dict(weights or {})
        self._default_weight = max(1e-6, float(default_weight))
        self._max_clients = max(1, int(max_clients))
        self._served: OrderedDict[str, float] = OrderedDict()
        # clients with waiting work, per shard (one shard per replica —
        # a fleet-shared ledger unions them): refreshed wholesale by each
        # shard's scheduler pass rather than inc/dec bookkeeping, so a
        # missed exit path can never leak an "active" client forever
        self._active: dict[str, frozenset[str]] = {}

    def weight(self, client: str) -> float:
        w = self._weights.get(client, self._default_weight)
        return w if w > 0 else self._default_weight

    def set_weight(self, client: str, weight: float) -> None:
        with self._lock:
            self._weights[client] = max(1e-6, float(weight))

    def _active_union(self) -> set[str]:
        out: set[str] = set()
        for clients in self._active.values():
            out |= clients
        return out

    def _floor(self) -> float:
        """Min counter among clients with waiting work (0 when none)."""
        vals = [
            self._served[c] for c in self._active_union() if c in self._served
        ]
        return min(vals) if vals else 0.0

    def set_active(self, shard: str, clients: set[str]) -> None:
        """Refresh the waiting-client set for one shard (replica). The
        new-arrival floor considers the union across shards."""
        with self._lock:
            if clients:
                self._active[shard] = frozenset(clients)
            else:
                self._active.pop(shard, None)

    def touch(self, client: str) -> None:
        """A request from `client` entered a waiting queue: lift its
        counter to the active floor (new-arrival / return-from-idle
        rule) — an idle period banks no credit, and a flood cannot be
        beaten by reconnecting under a fresh name with zero debt."""
        with self._lock:
            floor = self._floor()
            cur = self._served.get(client)
            self._served[client] = floor if cur is None else max(cur, floor)
            self._served.move_to_end(client)
            while len(self._served) > self._max_clients:
                # evict the LEAST-debt entry, not the least-recently
                # touched one: LRU would let a flooder spray max_clients
                # spoofed ids to push its own heavy counter out and
                # re-enter at the floor with laundered debt. Least-debt
                # eviction discards exactly the entries whose loss is
                # free (a fresh client re-enters at the floor anyway)
                # and keeps the heavy hitters' history.
                victim = min(self._served, key=self._served.get)
                del self._served[victim]

    def charge(self, client: str, tokens: int) -> None:
        """Bill `tokens` of served work to `client` at its weight."""
        if tokens <= 0:
            return
        with self._lock:
            self._served[client] = (
                self._served.get(client, self._floor())
                + tokens / self.weight(client)
            )
            self._served.move_to_end(client)

    def counter(self, client: str) -> float:
        """The ordering key: weighted tokens served so far (new clients
        read the active floor, which is what touch() would set)."""
        with self._lock:
            v = self._served.get(client)
            return self._floor() if v is None else v

    def counters_for(self, clients: set[str]) -> dict[str, float]:
        """Bulk ordering keys under ONE lock acquisition with the floor
        computed once — the scheduler sorts its whole waiting queue per
        pass, and per-request counter() calls would contend the
        fleet-shared lock O(waiting x shards*clients) times."""
        with self._lock:
            floor = self._floor()
            return {c: self._served.get(c, floor) for c in clients}

    def debt_spread(self) -> float:
        """Max - min counter across clients with waiting work: 0 when
        service is perfectly balanced (or <2 active clients), growing as
        one backlogged client falls behind another. The
        app_llm_fairness_debt gauge."""
        with self._lock:
            vals = [
                self._served[c]
                for c in self._active_union()
                if c in self._served
            ]
            if len(vals) < 2:
                return 0.0
            return max(vals) - min(vals)

    def snapshot(self) -> dict:
        """debug_state()["fairness"] payload (bounded at 32 rows)."""
        with self._lock:
            active = self._active_union()
            vals = [self._served[c] for c in active if c in self._served]
            rows = sorted(self._served.items(), key=lambda kv: kv[1])
            return {
                "clients": len(self._served),
                "active": len(active),
                "debt_spread": (
                    max(vals) - min(vals) if len(vals) >= 2 else 0.0
                ),
                "counters": {c: round(v, 1) for c, v in rows[:32]},
                "weights": dict(self._weights),
            }


class RetryBudget:
    """Token bucket bounding router-side retries. ``rate`` tokens/s
    refill up to ``burst``; every retry (failover re-dispatch, mid-submit
    replica-death retry) must :meth:`take` one. An empty bucket surfaces
    the ORIGINAL error instead of retrying — under overload a retry is
    new offered load aimed at the replicas least able to absorb it.

    ``rate=0`` with ``burst=0`` disables retries entirely; the default
    (1/s, burst 10) absorbs a replica death without ever amplifying a
    sustained failure into a storm.
    """

    def __init__(
        self,
        rate: float = 1.0,
        burst: float = 10.0,
        *,
        now_fn=time.monotonic,
    ):
        self._lock = threading.Lock()
        self.rate = max(0.0, float(rate))
        self.burst = max(0.0, float(burst))
        self._now = now_fn
        self._tokens = self.burst
        self._last = self._now()

    def _refill(self, now: float) -> None:
        if self.rate > 0 and now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
        self._last = now

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self._now())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def remaining(self) -> float:
        with self._lock:
            self._refill(self._now())
            return self._tokens


class OverloadController:
    """Degrade-then-shed: the brownout/shed state machine one engine (or
    one fleet router) consults at every admission.

    Inputs are predicted queue wait estimates (seconds) fed through
    :meth:`observe`. Two thresholds, strictly ordered:

    - ``brownout_wait_s`` (< shed): predicted wait above it for
      ``brownout_hold_s`` CONTINUOUS seconds engages brownout — new
      batch-class requests get ``max_new_tokens`` clamped to
      ``brownout_max_new``. Below half the threshold for the same hold,
      brownout disengages (hysteresis: flapping at the boundary would
      alternate clamped and unclamped answers request-to-request).
    - ``shed_wait_s``: predicted wait above it sheds the request NOW
      with ``retry_after = predicted - shed_wait_s`` (the time the
      backlog needs to drain back under the threshold), floored at
      ``min_retry_after``.

    Either threshold can be 0 (disabled). A zero ``brownout_hold_s``
    engages/disengages instantly (how the faked-clock tests drive it).
    """

    def __init__(
        self,
        *,
        shed_wait_s: float = 0.0,
        brownout_wait_s: float = 0.0,
        brownout_max_new: int = 0,
        brownout_hold_s: float = 2.0,
        min_retry_after: float = 0.5,
        now_fn=time.monotonic,
    ):
        self.shed_wait_s = max(0.0, float(shed_wait_s))
        self.brownout_wait_s = max(0.0, float(brownout_wait_s))
        self.brownout_max_new = max(0, int(brownout_max_new))
        self.brownout_hold_s = max(0.0, float(brownout_hold_s))
        self.min_retry_after = max(0.0, float(min_retry_after))
        self._now = now_fn
        self._lock = threading.Lock()
        self._over_since: float | None = None
        self._under_since: float | None = None
        self.brownout = False
        self.brownout_entries = 0  # times brownout engaged (telemetry)

    def enabled(self) -> bool:
        return self.shed_wait_s > 0 or (
            self.brownout_wait_s > 0 and self.brownout_max_new > 0
        )

    def observe(self, wait_s: float | None) -> None:
        """Feed one predicted-wait sample; advances the brownout state
        machine. None (no throughput estimate yet) counts as no
        pressure."""
        if self.brownout_wait_s <= 0 or self.brownout_max_new <= 0:
            return
        w = wait_s or 0.0
        now = self._now()
        with self._lock:
            if not self.brownout:
                if w > self.brownout_wait_s:
                    if self._over_since is None:
                        self._over_since = now
                    if now - self._over_since >= self.brownout_hold_s:
                        self.brownout = True
                        self.brownout_entries += 1
                        self._under_since = None
                else:
                    self._over_since = None
            else:
                if w < 0.5 * self.brownout_wait_s:
                    if self._under_since is None:
                        self._under_since = now
                    if now - self._under_since >= self.brownout_hold_s:
                        self.brownout = False
                        self._over_since = None
                else:
                    self._under_since = None

    def clamp(self, max_new_tokens: int, priority: str) -> int:
        """Brownout degrade: batch-class requests get shorter answers
        while the mode holds; interactive requests are never clamped
        (their latency is the thing brownout exists to protect)."""
        if (
            self.brownout
            and priority == "batch"
            and self.brownout_max_new > 0
        ):
            return min(max_new_tokens, self.brownout_max_new)
        return max_new_tokens

    def should_shed(self, wait_s: float | None) -> float | None:
        """Returns the Retry-After seconds when `wait_s` crosses the
        shed threshold, else None. Shed fires only past the DEGRADE
        stage: with brownout configured, requests are shed only while
        brownout is already active (degrade, then shed)."""
        if self.shed_wait_s <= 0 or wait_s is None:
            return None
        if wait_s <= self.shed_wait_s:
            return None
        if self.brownout_wait_s > 0 and self.brownout_max_new > 0:
            if not self.brownout:
                return None  # still in (or entering) the degrade stage
        return max(self.min_retry_after, wait_s - self.shed_wait_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled(),
                "brownout": self.brownout,
                "brownout_entries": self.brownout_entries,
                "shed_wait_s": self.shed_wait_s,
                "brownout_wait_s": self.brownout_wait_s,
                "brownout_max_new": self.brownout_max_new,
            }
