"""Replica supervisor: rebuild dead replicas under capped backoff.

Before this module a dead replica was permanent: ``ReplicatedLLMEngine``
stopped routing NEW work to it (llm.py ``_pick``) but nothing ever
rebuilt it, so one XLA fault cost a replica's worth of fleet capacity
for the rest of the process lifetime. The supervisor closes the loop the
way the reference repo's circuit breaker does for outbound services —
background probes that return a recovered endpoint to rotation — except
a dead engine cannot "recover": its threads are gone, so recovery means
CONSTRUCTING a replacement (params re-placed on the same device/submesh,
executables re-warmed) and swapping it into the routing set.

Policy: capped exponential backoff per replica slot
(``TPU_LLM_RESTART_BACKOFF_S`` doubling to
``TPU_LLM_RESTART_BACKOFF_MAX_S``), reset on a successful build. A
DRAINING fleet never restarts — the process is going down; rebuilding a
replica there would fight the rolling deploy. Restarts are counted in
``app_llm_replica_restarts_total`` and the per-slot state is visible in
``debug_state()["supervisor"]``.
"""

from __future__ import annotations

import threading
import time

__all__ = ["ReplicaSupervisor"]


class ReplicaSupervisor:
    """Monitor thread over a ReplicatedLLMEngine's replica slots.

    The fleet owns construction (``fleet._build_replica(i)`` carries the
    per-slot device/mesh spec and the failover-hook wiring); the
    supervisor owns only the WHEN: detect death, wait out the backoff,
    swap the replacement in, escalate the backoff on a failed build.
    """

    def __init__(
        self,
        fleet,
        *,
        interval_s: float = 0.5,
        backoff_s: float = 1.0,
        backoff_max_s: float = 30.0,
    ):
        self.fleet = fleet
        self.interval = interval_s
        self.backoff0 = backoff_s
        self.backoff_max = backoff_max_s
        self.restarts = 0
        self.restart_failures = 0
        self._stop = False
        # per-slot restart state: {slot: {"backoff": s, "next_try": t,
        # "building": bool, "failures": n}}
        self._state: dict[int, dict] = {}
        self._thread = threading.Thread(
            target=self._run, name="llm-replica-supervisor", daemon=True
        )
        self._thread.start()

    # -- monitor loop -----------------------------------------------------
    def _run(self) -> None:
        while not self._stop:
            try:
                self._scan()
            except Exception as e:  # noqa: BLE001 — supervisor must survive
                log = getattr(self.fleet, "logger", None)
                if log is not None:
                    log.error(f"replica supervisor scan failed: {e!r}")
            time.sleep(self.interval)

    def _scan(self) -> None:
        fleet = self.fleet
        if self._stop or getattr(fleet, "_draining", False):
            return
        now = time.perf_counter()
        for i, eng in enumerate(list(fleet.engines)):
            if eng.alive():
                self._state.pop(i, None)
                continue
            st = self._state.setdefault(
                i, {"backoff": self.backoff0, "next_try": now + self.backoff0,
                    "failures": 0},
            )
            if now < st["next_try"]:
                continue
            self._rebuild(i, st)

    def _rebuild(self, i: int, st: dict) -> None:
        fleet = self.fleet
        log = getattr(fleet, "logger", None)
        if log is not None:
            log.warn(f"replica supervisor: rebuilding dead replica {i}")
        t0 = time.perf_counter()
        try:
            replacement = fleet._build_replica(i)
        except Exception as e:  # noqa: BLE001 — the device may still be sick
            self.restart_failures += 1
            st["failures"] += 1
            st["backoff"] = min(st["backoff"] * 2.0, self.backoff_max)
            st["next_try"] = time.perf_counter() + st["backoff"]
            if log is not None:
                log.error(
                    f"replica {i} rebuild failed ({e!r}); next attempt in "
                    f"{st['backoff']:.1f}s"
                )
            return
        if self._stop or getattr(fleet, "_draining", False):
            # raced a close/drain: the fleet is going down — do not route
            # to (or leak) the replacement
            replacement.close()
            return
        fleet.engines[i] = replacement  # atomic item swap: routers see old or new
        self._state.pop(i, None)
        self.restarts += 1
        if fleet.metrics is not None:
            fleet.metrics.increment_counter(
                "app_llm_replica_restarts_total", model=fleet.label
            )
        if log is not None:
            log.info(
                f"replica {i} restarted and routed back in "
                f"{time.perf_counter() - t0:.1f}s"
            )

    # -- introspection / lifecycle ---------------------------------------
    def snapshot(self) -> dict:
        # list() guards against the supervisor thread resizing the dict
        # mid-iteration; the values are read torn-tolerantly (debug view)
        per_slot = {
            i: {
                "backoff_s": round(st["backoff"], 2),
                "failures": st["failures"],
                "retry_in_s": round(
                    max(0.0, st["next_try"] - time.perf_counter()), 2
                ),
            }
            for i, st in list(self._state.items())
        }
        return {
            "restarts": self.restarts,
            "restart_failures": self.restart_failures,
            "interval_s": self.interval,
            "pending": per_slot,
        }

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=5)
