"""Replica supervisor: rebuild dead replicas — elastically, behind a
canary gate, with device-health judgment.

Before this module a dead replica was permanent: ``ReplicatedLLMEngine``
stopped routing NEW work to it (llm.py ``_pick``) but nothing ever
rebuilt it. The first supervisor closed that loop with capped backoff on
the SAME device/submesh — which re-opened it for a persistently sick
chip: an HBM ECC fault or wedged ICI link turns same-device restart into
an infinite crash loop that silently costs the fleet a replica. This
version adds the judgment layer (gofr_tpu.resilience.health, mirroring
the reference repo's circuit breaker: trip, isolate, probe,
reintegrate):

- every replica death is CLASSIFIED and recorded against the device the
  engine ran on; the :class:`DeviceHealthLedger` quarantines a device
  after K attributable failures in a sliding window.
- **elastic rebuild**: a replica whose device is quarantined rebuilds
  from the retained host params on an alternate healthy device — a
  tensor-parallel replica on an alternate SAME-SIZE submesh of usable,
  unoccupied chips (``fleet._alternate_submesh_spec``; docs/
  advanced-guide/sharded-serving.md) — and when no alternate exists the
  slot is PARKED (capacity-degraded and visible as such —
  ``app_llm_replicas_parked``, health "degraded") instead of
  crash-looping, and restored the moment a device becomes usable again.
- **canary gate**: every rebuilt replica must pass the fixed greedy
  probe (health.canary_check — token-compared against a healthy replica
  when one exists) BEFORE it re-enters routing; a passing probe on a
  probation device reintegrates it, a failing one re-quarantines it.
- ``TPU_LLM_RESTART_MAX_ATTEMPTS`` consecutive failed rebuilds mark the
  slot permanently failed (``app_llm_replicas_failed``) — an operator
  page, not an eternal backoff.

Policy: capped exponential backoff per replica slot
(``TPU_LLM_RESTART_BACKOFF_S`` doubling to
``TPU_LLM_RESTART_BACKOFF_MAX_S``), reset on a successful build. A
DRAINING fleet never restarts — the process is going down; rebuilding a
replica there would fight the rolling deploy. Restarts are counted in
``app_llm_replica_restarts_total`` and the per-slot state is visible in
``debug_state()["supervisor"]``.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["ReplicaSupervisor"]


class ReplicaSupervisor:
    """Monitor thread over a ReplicatedLLMEngine's replica slots.

    The fleet owns construction and placement policy
    (``fleet._build_replica(i, spec=...)`` carries the device/mesh spec
    and failover-hook wiring; ``fleet._spec_for_rebuild(i)`` consults
    the health ledger for the target device; ``fleet._canary_check``
    judges the result); the supervisor owns the WHEN and the slot state
    machine: detect death, record it, wait out the backoff, gate the
    replacement, swap it in — or park/permanently-fail the slot when
    placement or the gate says no.
    """

    def __init__(
        self,
        fleet,
        *,
        interval_s: float = 0.5,
        backoff_s: float = 1.0,
        backoff_max_s: float = 30.0,
        max_attempts: int | None = None,
    ):
        self.fleet = fleet
        self.interval = interval_s
        self.backoff0 = backoff_s
        self.backoff_max = backoff_max_s
        if max_attempts is None:
            max_attempts = int(
                os.environ.get("TPU_LLM_RESTART_MAX_ATTEMPTS", "8") or 0
            )
        self.max_attempts = max(0, max_attempts)  # 0 = unlimited
        self.restarts = 0
        self.restart_failures = 0
        self.canary_rejects = 0  # rebuilds refused routing by the gate
        self._stop = False
        # per-slot restart state: {slot: {"backoff": s, "next_try": t,
        # "failures": n, "parked": bool, "failed": bool, "reason": str}}
        self._state: dict[int, dict] = {}
        self._thread = threading.Thread(
            target=self._run, name="llm-replica-supervisor", daemon=True
        )
        self._thread.start()

    # -- monitor loop -----------------------------------------------------
    def _run(self) -> None:
        while not self._stop:
            try:
                self._scan()
            except Exception as e:  # noqa: BLE001 — supervisor must survive
                log = getattr(self.fleet, "logger", None)
                if log is not None:
                    log.error(f"replica supervisor scan failed: {e!r}")
            time.sleep(self.interval)

    def _scan(self) -> None:
        fleet = self.fleet
        if self._stop or getattr(fleet, "_draining", False):
            return
        now = time.perf_counter()
        health = getattr(fleet, "health", None)
        # slots the rollout controller holds are being drained/rebuilt ON
        # PURPOSE (gofr_tpu.resilience.rollout): rebuilding one here
        # would race the controller's close->build->gate->swap sequence
        hold = getattr(fleet, "_rollout_hold", ())
        for i, eng in enumerate(list(fleet.engines)):
            if i in hold:
                continue
            if eng.alive():
                if self._state.pop(i, None) is not None:
                    self._observe_slots()
                continue
            st = self._state.get(i)
            if st is None:
                st = {"backoff": self.backoff0,
                      "next_try": now + self.backoff0,
                      "failures": 0, "parked": False, "failed": False,
                      "reason": None}
                self._state[i] = st
                # classify this death and bill the device the engine was
                # actually running on (elastic rebuilds may have moved it
                # off its home device)
                if health is not None:
                    why = getattr(eng, "died_reason", None)
                    health.record_failure(
                        fleet._current_keys[i], health.classify(why),
                        detail=why or "",
                    )
            if st["failed"]:
                continue  # permanently failed: operator territory
            if st["parked"]:
                # reintegration restores capacity: the instant ANY device
                # becomes usable for this slot (home cooldown elapsed, an
                # alternate freed/reintegrated), leave the parking lot
                if fleet._spec_for_rebuild(i) is None:
                    continue
                st["parked"] = False
                st["reason"] = None
                st["next_try"] = now
                self._observe_slots()
            if now < st["next_try"]:
                continue
            self._rebuild(i, st)

    def _rebuild(self, i: int, st: dict) -> None:
        fleet = self.fleet
        log = getattr(fleet, "logger", None)
        picked = fleet._spec_for_rebuild(i)
        if picked is None:
            # no usable device anywhere: park — a visible capacity
            # degradation (gauge + degraded health), NOT a crash loop;
            # the scan re-checks placement every interval
            st["parked"] = True
            st["reason"] = "parked: no usable device (home quarantined, no alternate)"
            self._observe_slots()
            if log is not None:
                log.error(f"replica {i} parked: no usable device for rebuild")
            return
        spec, key = picked
        if log is not None:
            home = key == fleet._device_keys[i]
            log.warn(
                f"replica supervisor: rebuilding dead replica {i} on "
                f"{key}{'' if home else ' (alternate device)'}"
            )
        t0 = time.perf_counter()
        try:
            replacement = fleet._build_replica(i, spec=spec)
        except Exception as e:  # noqa: BLE001 — the device may still be sick
            self._rebuild_failed(i, st, key, f"build failed: {e!r}")
            return
        try:
            ok, detail = fleet._canary_check(replacement)
        except Exception as e:  # noqa: BLE001 — a crashing gate must not leak the engine
            ok, detail = False, f"canary crashed: {e!r}"
        if not ok:
            # a half-sick rebuild must never receive live traffic: close
            # it and treat the gate rejection exactly like a failed build
            # (device billed, backoff escalated, attempts counted)
            self.canary_rejects += 1
            try:
                replacement.close()
            except Exception:  # noqa: BLE001 — teardown must not mask the verdict
                pass
            self._rebuild_failed(i, st, key, f"canary rejected: {detail}")
            return
        if self._stop or getattr(fleet, "_draining", False):
            # raced a close/drain: the fleet is going down — do not route
            # to (or leak) the replacement
            replacement.close()
            return
        if (
            i in getattr(fleet, "_rollout_hold", ())
            or fleet.engines[i].alive()
        ):
            # raced the rollout controller: the slot was (re)claimed —
            # held for a shift/rollback, or already carrying a live
            # engine the controller swapped in — while our multi-second
            # build ran. Clobbering it would orphan a live engine
            # (leaked threads + a full device-resident weight copy);
            # discard ours instead. The controller holds the slot for
            # its whole swap sequence, so this last check cannot pass
            # mid-swap.
            replacement.close()
            self._state.pop(i, None)
            return
        fleet.engines[i] = replacement  # atomic item swap: routers see old or new
        fleet._current_keys[i] = key
        health = getattr(fleet, "health", None)
        if health is not None:
            health.probe_ok(key)  # reintegrates a probation device; no-op else
        self._state.pop(i, None)
        self.restarts += 1
        self._observe_slots()
        if fleet.metrics is not None:
            fleet.metrics.increment_counter(
                "app_llm_replica_restarts_total", model=fleet.label
            )
        if log is not None:
            log.info(
                f"replica {i} restarted on {key} and routed back in "
                f"{time.perf_counter() - t0:.1f}s"
            )

    def _rebuild_failed(self, i: int, st: dict, key: str, why: str) -> None:
        fleet = self.fleet
        log = getattr(fleet, "logger", None)
        self.restart_failures += 1
        st["failures"] += 1
        health = getattr(fleet, "health", None)
        if health is not None:
            # a failed rebuild is an attributable device failure: enough
            # of them quarantine the device, which reroutes the NEXT
            # attempt to an alternate instead of retrying the sick chip
            health.record_failure(key, "rebuild_failure", detail=why)
        if self.max_attempts and st["failures"] >= self.max_attempts:
            st["failed"] = True
            st["reason"] = (
                f"permanently failed after {st['failures']} rebuild "
                f"attempts (last: {why})"
            )
            self._observe_slots()
            if log is not None:
                log.error(f"replica {i} {st['reason']}")
            return
        st["backoff"] = min(st["backoff"] * 2.0, self.backoff_max)
        st["next_try"] = time.perf_counter() + st["backoff"]
        if log is not None:
            log.error(
                f"replica {i} rebuild on {key} failed ({why}); next attempt "
                f"in {st['backoff']:.1f}s"
            )

    # -- introspection / lifecycle ---------------------------------------
    def parked_count(self) -> int:
        return sum(1 for st in list(self._state.values()) if st.get("parked"))

    def failed_count(self) -> int:
        return sum(1 for st in list(self._state.values()) if st.get("failed"))

    def _observe_slots(self) -> None:
        """Keep the capacity-degradation gauges live: parked and
        permanently-failed slots are exactly what the health endpoint
        and dashboards alert on."""
        metrics = getattr(self.fleet, "metrics", None)
        if metrics is None:
            return
        metrics.set_gauge(
            "app_llm_replicas_parked", float(self.parked_count()),
            model=self.fleet.label,
        )
        metrics.set_gauge(
            "app_llm_replicas_failed", float(self.failed_count()),
            model=self.fleet.label,
        )

    def snapshot(self) -> dict:
        # list() guards against the supervisor thread resizing the dict
        # mid-iteration; the values are read torn-tolerantly (debug view)
        per_slot = {}
        for i, st in list(self._state.items()):
            row = {
                "backoff_s": round(st["backoff"], 2),
                "failures": st["failures"],
                "retry_in_s": round(
                    max(0.0, st["next_try"] - time.perf_counter()), 2
                ),
                "parked": st["parked"],
                "failed": st["failed"],
            }
            if st.get("reason"):
                row["reason"] = st["reason"]
            per_slot[i] = row
        return {
            "restarts": self.restarts,
            "restart_failures": self.restart_failures,
            "canary_rejects": self.canary_rejects,
            "max_attempts": self.max_attempts,
            "parked": self.parked_count(),
            "failed": self.failed_count(),
            "interval_s": self.interval,
            "pending": per_slot,
        }

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=5)
