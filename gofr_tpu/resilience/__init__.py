"""Resilience: fault injection, step watchdog, replica supervision, drain.

The reference repo frames resilience as a first-class capability — its
outbound HTTP client carries a circuit breaker with background health
probes, and every app exposes liveness/readiness checks. This package is
the TPU-serving counterpart BELOW the HTTP layer, where the failure
modes are different: an XLA fault kills an engine thread, an HBM OOM
aborts an admission, a wedged transfer hangs a step forever, and a
process being rolled must finish in-flight decodes before dying.

Pieces (docs/advanced-guide/resilience.md has the failure model):

- :class:`FaultInjector` (faults.py) — named failure points toggled per
  point via the Python API or ``TPU_LLM_FAULTS``, so every recovery path
  is exercised deterministically in tier-1 and the CI chaos smoke.
- :class:`Heartbeat` / :class:`StepWatchdog` (watchdog.py) — convert a
  device step exceeding ``TPU_LLM_STEP_WATCHDOG_S`` into a replica death
  with a distinct reason (a hang used to block invisibly forever).
- :class:`ReplicaSupervisor` (supervisor.py) — rebuild dead replicas
  under capped exponential backoff and return them to the routing set,
  elastically: placement consults the device-health ledger, rebuilds
  land on alternate healthy devices, and every replacement passes the
  canary gate before it is routed.
- :class:`DeviceHealthLedger` / :func:`canary_check` (health.py) — the
  per-device failure ledger (classify, quarantine, cooldown, probe,
  reintegrate — the circuit-breaker state machine at TPU-device level)
  and the fixed greedy probe that keeps a half-sick rebuild out of the
  routing set.
- In-flight failover, poison-request quarantine, the numerical
  watchdog, and graceful drain live in ``gofr_tpu.llm`` /
  ``gofr_tpu.app`` (they ARE the engine/app lifecycle); this package
  owns their metrics registration so the series exist wherever any
  resilience feature is active.
"""

from __future__ import annotations

import threading

from .faults import FAULT_POINTS, FaultInjector, InjectedFault, default_injector
from .health import (
    DeviceHealthLedger,
    canary_check,
    device_key,
    spec_device_key,
    split_device_key,
)
from .overload import FairLedger, OverloadController, RetryBudget
from .rollout import ModelHandle, RolloutController, RolloutError, RolloutInProgress
from .supervisor import ReplicaSupervisor
from .watchdog import Heartbeat, StepWatchdog

__all__ = [
    "FAULT_POINTS",
    "DeviceHealthLedger",
    "FairLedger",
    "FaultInjector",
    "Heartbeat",
    "InjectedFault",
    "ModelHandle",
    "OverloadController",
    "ReplicaSupervisor",
    "RetryBudget",
    "RolloutController",
    "RolloutError",
    "RolloutInProgress",
    "StepWatchdog",
    "canary_check",
    "default_injector",
    "device_key",
    "register_resilience_metrics",
    "spec_device_key",
    "split_device_key",
]

# Serializes registration across engines (replicas register concurrently;
# same rationale as llm.py's _OBS_REG_LOCK).
_REG_LOCK = threading.Lock()


def register_resilience_metrics(metrics) -> None:
    """The resilience instrument set, registered once per process (series
    separate by the model label). Counters are monotone trip/restart
    tallies; the drain gauge is the rolling-deploy signal (0 serving,
    1 draining)."""
    with _REG_LOCK:
        for name, desc in (
            ("app_llm_replica_restarts_total",
             "llm replicas rebuilt and routed back by the supervisor"),
            ("app_llm_failovers_total",
             "llm in-flight requests re-dispatched off a dead replica"),
            ("app_llm_failover_errors_total",
             "llm failover requests errored out (no live replica or "
             "retry budget exhausted)"),
            ("app_llm_watchdog_trips_total",
             "llm device steps converted to replica death by the step "
             "watchdog"),
            ("app_llm_deadline_cancels_total",
             "llm requests cancelled mid-flight because their deadline "
             "passed"),
            ("app_llm_faults_injected_total",
             "faults fired by the injection harness (chaos only)"),
            ("app_llm_preemptions_total",
             "llm batch-class requests preempted (slot freed, requeued "
             "as a continuation) to admit interactive traffic"),
            ("app_llm_sheds_predicted_total",
             "llm requests shed at submit because predicted queue wait "
             "crossed the shed threshold (429 + Retry-After)"),
            ("app_llm_fleet_rejected_total",
             "llm requests rejected at the fleet queued-token admission "
             "cap (429 + Retry-After)"),
            ("app_llm_device_quarantines_total",
             "TPU devices quarantined by the health ledger (K "
             "attributable failures inside the sliding window)"),
            ("app_llm_numerical_trips_total",
             "llm device steps whose logits went non-finite, converted "
             "to replica death by the numerical watchdog"),
            ("app_llm_poison_requests_total",
             "llm requests refused further failover after being in "
             "flight across the poison death threshold (500/INTERNAL "
             "to the caller)"),
            # model lifecycle (resilience.rollout;
            # docs/advanced-guide/rollouts.md)
            ("app_llm_rollouts_started_total",
             "llm weight rollouts staged (deploy()/the admin route)"),
            ("app_llm_rollouts_completed_total",
             "llm weight rollouts fully shifted and baked clean"),
            ("app_llm_rollouts_rolled_back_total",
             "llm weight rollouts rolled back to the old version "
             "(canary/shadow rejection or bake-window regression)"),
            ("app_llm_requests_by_version_total",
             "llm requests finished, by model version and finish "
             "reason — the per-version error-rate view during a "
             "traffic shift"),
            ("app_llm_disconnect_cancels_total",
             "llm requests cancelled because the serving edge detected "
             "a dead peer (broken pipe / closed gRPC context) — slot "
             "freed instead of decoding to completion"),
        ):
            if not metrics.has(name):
                metrics.new_counter(name, desc)
        for name, desc in (
            ("app_llm_drain_state",
             "llm engine drain state (0 serving, 1 draining)"),
            ("app_llm_brownout_state",
             "llm brownout mode (0 normal, 1 batch max_new_tokens "
             "clamped under sustained pressure)"),
            ("app_llm_fairness_debt",
             "spread (max-min) of weighted served-token counters across "
             "clients with waiting work — 0 is perfectly fair"),
            ("app_llm_retry_budget_remaining",
             "router retry-budget tokens remaining (token bucket; 0 "
             "means retries surface the original error)"),
            ("app_llm_devices_quarantined",
             "TPU devices currently quarantined or awaiting a "
             "successful reintegration probe"),
            ("app_llm_replicas_parked",
             "llm replica slots parked for lack of a usable device "
             "(capacity-degraded, not crash-looping; health reports "
             "degraded)"),
            ("app_llm_replicas_failed",
             "llm replica slots permanently failed after "
             "TPU_LLM_RESTART_MAX_ATTEMPTS consecutive rebuild "
             "failures (operator attention required)"),
            ("app_llm_model_version_info",
             "live replicas serving each model version (single engine: "
             "1 for its version); mixed only mid-rollout, 0 after close"),
            ("app_llm_rollout_state",
             "llm rollout state machine (0 idle/terminal, 1 shifting, "
             "2 baking, 3 rolling back)"),
        ):
            if not metrics.has(name):
                metrics.new_gauge(name, desc)
