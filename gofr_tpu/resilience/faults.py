"""Deterministic fault injection for the serving path.

The recovery code in gofr_tpu.llm (per-iteration scheduler recovery,
collector fetch retries, replica failover, the step watchdog, supervised
restart) is exactly the code that never runs in a healthy test
environment — a CPU backend does not throw XLA faults on demand. The
injector gives every recovery path a named, countable trigger so tier-1
tests and the CI chaos smoke (scripts/smoke_chaos.py) exercise them
deterministically, the same way the reference repo's circuit breaker is
driven by a fake failing service rather than a real outage.

Named failure points (armed per point, optionally per engine label):

- ``device_step``    — raise ``InjectedFault`` at the next device-step
                       dispatch (scheduler-side; exercises per-iteration
                       recovery + stranded-request requeue).
- ``step_latency``   — sleep ``delay`` seconds inside the next device
                       fetch (collector-side; a hung step, the watchdog's
                       prey — the sleep happens OUTSIDE the engine lock,
                       like a real wedged transfer).
- ``admission_oom``  — raise at the next admission before any slot is
                       assigned (exercises ``_requeue_stranded``).
- ``replica_kill``   — the next scheduler pass calls ``_die`` (terminal
                       replica death; exercises in-flight failover and
                       supervised restart).
- ``overload_pressure`` — the next submit() sees a predicted queue wait
                       of ``delay`` seconds (default 3600) regardless of
                       the real backlog, driving the brownout/shed
                       overload controller deterministically (exercises
                       predicted-wait shedding, Retry-After computation,
                       and brownout engagement without constructing real
                       queue pressure).
- ``nan_logits``     — corrupt one fetched step's sampled tokens with
                       the numerical-watchdog sentinel (what NaN/Inf
                       logits produce on device; exercises the
                       numerical watchdog -> replica death -> failover
                       path, or — with the watchdog disabled — the
                       silent-garbage-with-200 failure it exists to
                       prevent).
- ``device_sick``    — raise at replica (re)build on a matching device
                       (label-match against the device key, e.g.
                       "cpu:0"); persistent arming (count=-1) models a
                       chip that fails every rebuild, driving device
                       quarantine, elastic rebuild on an alternate
                       device, and slot parking deterministically.
- ``rollout_canary_fail`` — the rollout controller's admission gate
                       rejects the next candidate replica as if its
                       canary/shadow probe diverged (deterministic
                       automatic-rollback path for a bad weight push;
                       gofr_tpu.resilience.rollout).
- ``rollout_bake_regression`` — the next rollout bake-window poll sees
                       a regression regardless of real fleet health,
                       driving the post-shift rollback path
                       deterministically in tier-1 and CI.

A spec may carry a ``tag``: it then fires only for a request whose
``GenRequest.tag`` equals it (the poison-payload marker — a tagged
``device_step`` kills exactly the replica serving the tagged request,
driving the router's poison-request quarantine).

Arming: the Python API (``injector.arm(point, ...)``) for tests and the
chaos smoke, or the ``TPU_LLM_FAULTS`` env var for a black-box process —
a comma list of ``point[=count[:delay_s]][@label]`` entries parsed once
when the process-default injector is first built, e.g.
``TPU_LLM_FAULTS="replica_kill=1,step_latency=1:5.0,device_sick=3@cpu:0"``.

A disarmed injector costs one dict lookup per check — the seams stay in
production code (the same argument as the reference keeping its circuit
breaker in the client, not in a test build).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

__all__ = ["FaultInjector", "InjectedFault", "default_injector", "FAULT_POINTS"]

FAULT_POINTS = (
    "device_step",
    "step_latency",
    "admission_oom",
    "replica_kill",
    "overload_pressure",
    "nan_logits",
    "device_sick",
    "rollout_canary_fail",
    "rollout_bake_regression",
)


class InjectedFault(RuntimeError):
    """Raised by an armed raise-kind failure point. A distinct type so
    tests can tell an injected failure from a real one; engine recovery
    treats it like any other device error (that is the point)."""


@dataclass
class _Spec:
    point: str
    count: int = 1  # fires remaining; <0 = unlimited
    # Engine-label anchor: exact label or suffix ("/r1" matches "llm/r1"
    # but NOT "llm/r10" — a substring match would kill the wrong replica
    # in fleets of >=10). None = any engine.
    label: str | None = None
    delay: float = 0.0  # step_latency sleep seconds
    message: str = ""
    # Poison-payload marker: a tagged spec fires ONLY when take() is
    # given the same tag (read off the request being dispatched), and an
    # untagged spec never fires for a tagged take — the two populations
    # are disjoint so arming a poison payload cannot leak into the plain
    # device_step chaos seam or vice versa.
    tag: str | None = None

    def matches(self, label: str) -> bool:
        return (
            self.label is None
            or label == self.label
            or label.endswith(self.label)
        )


class FaultInjector:
    """Thread-safe registry of armed failure points.

    Engines hold one injector (the process default unless a test passes
    its own) and call :meth:`take` at each seam; a hit decrements the
    armed count and is tallied in :meth:`fired`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, list[_Spec]] = {}
        self._fired: dict[str, int] = {}

    def arm(
        self,
        point: str,
        *,
        count: int = 1,
        label: str | None = None,
        delay: float = 0.0,
        message: str = "",
        tag: str | None = None,
    ) -> None:
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {FAULT_POINTS}"
            )
        spec = _Spec(point=point, count=count, label=label, delay=delay,
                     message=message or f"injected fault: {point}", tag=tag)
        with self._lock:
            self._armed.setdefault(point, []).append(spec)

    def disarm(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._armed.clear()
            else:
                self._armed.pop(point, None)

    def take(self, point: str, label: str = "", tag: str | None = None) -> _Spec | None:
        """One seam check: the first armed spec matching this engine label
        (and tag population — tagged specs fire only for the same tag,
        untagged specs only for tagless takes) fires (its count
        decrements); None when nothing is armed — the disarmed fast path
        is a single dict lookup under no lock."""
        if not self._armed:  # benign race: worst case one extra locked check
            return None
        with self._lock:
            specs = self._armed.get(point)
            if not specs:
                return None
            for spec in specs:
                if spec.tag != tag:
                    continue
                if not spec.matches(label):
                    continue
                if spec.count == 0:
                    continue
                if spec.count > 0:
                    spec.count -= 1
                self._fired[point] = self._fired.get(point, 0) + 1
                if spec.count == 0:
                    specs.remove(spec)
                    if not specs:
                        del self._armed[point]
                return spec
            return None

    def fired(self, point: str | None = None) -> int:
        with self._lock:
            if point is not None:
                return self._fired.get(point, 0)
            return sum(self._fired.values())

    def has_tagged(self, point: str) -> bool:
        """Any tagged spec armed for this point? The scheduler's poison
        seam pre-check — keeps the per-pass cost at one dict lookup
        while nothing is armed."""
        if not self._armed:
            return False
        with self._lock:
            return any(s.tag for s in self._armed.get(point, ()))

    def snapshot(self) -> dict:
        """Armed/fired view for debug_state()."""

        def row(s: _Spec) -> dict:
            out = {"count": s.count, "label": s.label, "delay": s.delay}
            if s.tag is not None:
                out["tag"] = s.tag
            return out

        with self._lock:
            return {
                "armed": {
                    p: [row(s) for s in specs]
                    for p, specs in self._armed.items()
                },
                "fired": dict(self._fired),
            }


@dataclass
class _DefaultHolder:
    injector: FaultInjector | None = None
    lock: threading.Lock = field(default_factory=threading.Lock)


_default = _DefaultHolder()


def _arm_from_env(inj: FaultInjector, raw: str, logger=None) -> None:
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        # ``@label`` is split FIRST: device keys ("cpu:0") contain the
        # count/delay separator, so the label must come off before the
        # left side is parsed as count[:delay]
        body, _, label = part.partition("@")
        point, _, rest = body.partition("=")
        count, delay = 1, 0.0
        if rest:
            cnt, _, d = rest.partition(":")
            try:
                count = int(cnt)
                if d:
                    delay = float(d)
            except ValueError:
                if logger is not None:
                    logger.warn(f"TPU_LLM_FAULTS: unparseable entry {part!r}")
                continue
        try:
            inj.arm(point.strip(), count=count, delay=delay,
                    label=label.strip() or None)
        except ValueError as e:
            if logger is not None:
                logger.warn(f"TPU_LLM_FAULTS: {e}")


def default_injector() -> FaultInjector:
    """Process-default injector, armed once from ``TPU_LLM_FAULTS``.
    Tests pass their own ``FaultInjector()`` to the engine instead of
    touching this shared instance."""
    if _default.injector is None:
        with _default.lock:
            if _default.injector is None:
                inj = FaultInjector()
                raw = os.environ.get("TPU_LLM_FAULTS", "")
                if raw:
                    _arm_from_env(inj, raw)
                _default.injector = inj
    return _default.injector


def sleep_for(spec: _Spec) -> None:
    """Serve a step_latency spec: a plain blocking sleep, exactly what a
    wedged device transfer looks like from the host."""
    if spec.delay > 0:
        time.sleep(spec.delay)
