"""gofr_tpu.testutil — test helpers.

Parity: reference pkg/gofr/testutil/ (os.go:8-37 stdout/stderr capture) plus
the service stand-ins its CI gets from containers (go.yml:61-91): MiniRedis
here plays the role miniredis plays in reference tests
(http-server/main_test.go:57-62) — a real in-process server speaking the
real wire protocol, so client code is tested against the protocol, not a
mock of itself.
"""

from __future__ import annotations

import asyncio
import contextlib
import fnmatch
import io
import sys
import threading
import time
from typing import Iterator


@contextlib.contextmanager
def capture_stdout() -> Iterator[io.StringIO]:
    """testutil.StdoutOutputForFunc (os.go:8-22) as a context manager."""
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        yield buf
    finally:
        sys.stdout = old


@contextlib.contextmanager
def capture_stderr() -> Iterator[io.StringIO]:
    buf = io.StringIO()
    old = sys.stderr
    sys.stderr = buf
    try:
        yield buf
    finally:
        sys.stderr = old


_CERT_CACHE: tuple[str, str] | None = None


def self_signed_cert() -> tuple[str, str]:
    """Generate (once per process) a self-signed localhost certificate and
    key, returning (cert_pem_path, key_pem_path). SANs cover localhost and
    127.0.0.1 so a verifying client context with cafile=cert_path passes
    full hostname checking — TLS tests exercise the real verification
    path, not verify_mode=CERT_NONE."""
    global _CERT_CACHE
    if _CERT_CACHE is not None:
        return _CERT_CACHE
    import datetime
    import ipaddress
    import tempfile

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.DNSName("localhost"),
                    x509.IPAddress(ipaddress.IPv4Address("127.0.0.1")),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    d = tempfile.mkdtemp(prefix="gofr-tls-")
    cert_path, key_path = f"{d}/cert.pem", f"{d}/key.pem"
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    _CERT_CACHE = (cert_path, key_path)
    return _CERT_CACHE


def server_tls_context():
    """ssl.SSLContext serving the self_signed_cert() pair."""
    import ssl

    cert, key = self_signed_cert()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    return ctx


def client_tls_context():
    """Verifying ssl.SSLContext trusting (only) the self_signed_cert()."""
    import ssl

    cert, _ = self_signed_cert()
    return ssl.create_default_context(cafile=cert)


class MiniRedis:
    """In-process RESP2 server on an ephemeral port (asyncio, own thread).

    Supports the command set the framework's Redis client exposes: strings
    (GET/SET/DEL/EXISTS/EXPIRE/TTL/INCR), hashes (HSET/HGET/HGETALL), lists
    (LPUSH/RPOP), KEYS, FLUSHDB, PING, INFO, SELECT.
    """

    def __init__(
        self,
        password: str | None = None,
        username: str | None = None,
        tls: bool = False,
    ):
        self.data: dict[bytes, object] = {}
        self.expiry: dict[bytes, float] = {}
        # password set -> connections must AUTH first (requirepass / ACL
        # semantics), exercising the client's auth handshake paths
        self.password = password
        self.username = username
        self.tls = tls  # serve over self_signed_cert() TLS
        self.port: int | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server = None
        self._started = threading.Event()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MiniRedis":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("MiniRedis failed to start")
        return self

    def _run(self) -> None:
        async def main():
            self._server = await asyncio.start_server(
                self._client, "127.0.0.1", 0,
                ssl=server_tls_context() if self.tls else None,
            )
            self.port = self._server.sockets[0].getsockname()[1]
            self._loop = asyncio.get_running_loop()
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        with contextlib.suppress(asyncio.CancelledError):
            asyncio.run(main())

    def stop(self) -> None:
        if self._loop is not None:
            for task in asyncio.all_tasks(self._loop):
                self._loop.call_soon_threadsafe(task.cancel)
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- protocol ---------------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        authed = self.password is None
        try:
            while True:
                line = (await reader.readline()).strip()
                if not line:
                    return
                assert line[:1] == b"*", line
                n = int(line[1:])
                parts = []
                for _ in range(n):
                    ln = (await reader.readline()).strip()
                    assert ln[:1] == b"$"
                    size = int(ln[1:])
                    parts.append((await reader.readexactly(size + 2))[:-2])
                if self.password is not None and parts[0].upper() == b"AUTH":
                    pw_ok = parts[-1].decode() == self.password
                    user_ok = len(parts) == 2 or parts[1].decode() == (
                        self.username or "default"
                    )
                    if pw_ok and user_ok:
                        authed = True
                        writer.write(self._simple("OK"))
                    else:
                        writer.write(b"-WRONGPASS invalid username-password pair\r\n")
                    await writer.drain()
                    continue
                if not authed:
                    writer.write(b"-NOAUTH Authentication required.\r\n")
                    await writer.drain()
                    continue
                writer.write(self._dispatch(parts))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, AssertionError):
            pass
        finally:
            with contextlib.suppress(Exception):
                writer.close()

    # -- encoding helpers -------------------------------------------------
    @staticmethod
    def _bulk(v: bytes | None) -> bytes:
        if v is None:
            return b"$-1\r\n"
        return b"$%d\r\n%s\r\n" % (len(v), v)

    @staticmethod
    def _int(v: int) -> bytes:
        return b":%d\r\n" % v

    @staticmethod
    def _simple(s: str) -> bytes:
        return b"+%s\r\n" % s.encode()

    @staticmethod
    def _err(s: str) -> bytes:
        return b"-ERR %s\r\n" % s.encode()

    @classmethod
    def _array(cls, items: list[bytes]) -> bytes:
        return b"*%d\r\n%s" % (len(items), b"".join(cls._bulk(i) for i in items))

    # -- command dispatch -------------------------------------------------
    def _alive(self, key: bytes) -> bool:
        exp = self.expiry.get(key)
        if exp is not None and exp <= time.time():
            self.data.pop(key, None)
            self.expiry.pop(key, None)
            return False
        return key in self.data

    def _dispatch(self, parts: list[bytes]) -> bytes:  # noqa: PLR0911, PLR0912
        cmd = parts[0].upper().decode()
        args = parts[1:]
        d = self.data
        if cmd == "PING":
            return self._simple("PONG")
        if cmd == "SELECT":
            return self._simple("OK")
        if cmd == "SET":
            d[args[0]] = args[1]
            self.expiry.pop(args[0], None)
            if len(args) >= 4 and args[2].upper() == b"EX":
                self.expiry[args[0]] = time.time() + int(args[3])
            return self._simple("OK")
        if cmd == "GET":
            v = d.get(args[0]) if self._alive(args[0]) else None
            return self._bulk(v if isinstance(v, (bytes, type(None))) else None)
        if cmd == "DEL":
            n = sum(1 for k in args if d.pop(k, None) is not None)
            return self._int(n)
        if cmd == "EXISTS":
            return self._int(sum(1 for k in args if self._alive(k)))
        if cmd == "EXPIRE":
            if args[0] in d:
                self.expiry[args[0]] = time.time() + int(args[1])
                return self._int(1)
            return self._int(0)
        if cmd == "TTL":
            if not self._alive(args[0]):
                return self._int(-2)
            exp = self.expiry.get(args[0])
            return self._int(-1 if exp is None else max(0, round(exp - time.time())))
        if cmd == "INCR":
            cur = int(d.get(args[0], b"0")) + 1
            d[args[0]] = str(cur).encode()
            return self._int(cur)
        if cmd == "HSET":
            h = d.setdefault(args[0], {})
            created = args[1] not in h
            h[args[1]] = args[2]
            return self._int(1 if created else 0)
        if cmd == "HGET":
            h = d.get(args[0]) or {}
            return self._bulk(h.get(args[1]) if isinstance(h, dict) else None)
        if cmd == "HGETALL":
            h = d.get(args[0]) or {}
            flat: list[bytes] = []
            if isinstance(h, dict):
                for k, v in h.items():
                    flat += [k, v]
            return self._array(flat)
        if cmd == "LPUSH":
            lst = d.setdefault(args[0], [])
            for v in args[1:]:
                lst.insert(0, v)
            return self._int(len(lst))
        if cmd == "RPOP":
            lst = d.get(args[0]) or []
            return self._bulk(lst.pop() if lst else None)
        if cmd == "KEYS":
            pat = args[0].decode()
            return self._array(
                [k for k in list(d) if self._alive(k) and fnmatch.fnmatch(k.decode(), pat)]
            )
        if cmd == "FLUSHDB":
            d.clear()
            self.expiry.clear()
            return self._simple("OK")
        if cmd == "INFO":
            body = (
                "# Stats\r\ntotal_connections_received:1\r\n"
                f"total_commands_processed:{len(d)}\r\n"
            )
            return self._bulk(body.encode())
        return self._err(f"unknown command '{cmd}'")
