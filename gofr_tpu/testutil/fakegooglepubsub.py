"""In-process fake Google Pub/Sub emulator: a real grpcio server with
generic (bytes-level) handlers speaking the same hand-rolled protobuf
codec as the client (datasource/pubsub/google.py) — the FakeKafkaBroker /
FakeMQTTBroker playbook applied to gRPC. Implements the google.pubsub.v1
subset the framework uses: CreateTopic, GetTopic, DeleteTopic, Publish,
CreateSubscription, Pull, Acknowledge.
"""

from __future__ import annotations

import collections
import itertools
import threading
from concurrent.futures import ThreadPoolExecutor

import grpc

from ..datasource.pubsub.google import pb

__all__ = ["FakeGooglePubSub"]


class _State:
    def __init__(self):
        self.topics: set[str] = set()
        self.subs: dict[str, str] = {}  # sub path -> topic path
        self.queues: dict[str, collections.deque] = {}  # sub -> deque[(ack, data, attrs)]
        self.unacked: dict[str, tuple] = {}  # ack_id -> (sub, record)
        self.acked: list[str] = []
        self.lock = threading.Lock()
        self.arrived = threading.Condition(lock=self.lock)  # publish signal
        self.ids = itertools.count(1)


class FakeGooglePubSub:
    def __init__(self, host: str = "127.0.0.1", *, no_streaming: bool = False):
        # no_streaming simulates an old emulator without StreamingPull, so
        # tests can cover the client's permanent unary-Pull fallback
        self.state = _State()
        self._server = grpc.server(ThreadPoolExecutor(max_workers=8))
        handlers = {
            "CreateTopic": self._create_topic,
            "GetTopic": self._get_topic,
            "DeleteTopic": self._delete_topic,
            "Publish": self._publish,
        }
        sub_handlers = {
            "CreateSubscription": self._create_subscription,
            "DeleteSubscription": self._delete_subscription,
            "Pull": self._pull,
            "Acknowledge": self._acknowledge,
        }
        stream_handlers = (
            {} if no_streaming else {"StreamingPull": self._streaming_pull}
        )
        self._server.add_generic_rpc_handlers(
            (
                _Generic("google.pubsub.v1.Publisher", handlers),
                _Generic(
                    "google.pubsub.v1.Subscriber", sub_handlers, stream_handlers
                ),
            )
        )
        self.port = self._server.add_insecure_port(f"{host}:0")
        self.host = host
        self._server.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        self._server.stop(grace=None)

    # -- handlers (bytes in, bytes out) ------------------------------------
    def _create_topic(self, body: bytes, ctx) -> bytes:
        name = pb.first(pb.decode(body), 1, b"").decode()
        with self.state.lock:
            if name in self.state.topics:
                ctx.abort(grpc.StatusCode.ALREADY_EXISTS, "topic exists")
            self.state.topics.add(name)
        return pb.str_field(1, name)

    def _get_topic(self, body: bytes, ctx) -> bytes:
        name = pb.first(pb.decode(body), 1, b"").decode()
        with self.state.lock:
            if name not in self.state.topics:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
        return pb.str_field(1, name)

    def _delete_topic(self, body: bytes, ctx) -> bytes:
        name = pb.first(pb.decode(body), 1, b"").decode()
        with self.state.lock:
            if name not in self.state.topics:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
            self.state.topics.discard(name)
            for sub, t in list(self.state.subs.items()):
                if t == name:
                    del self.state.subs[sub]
                    self.state.queues.pop(sub, None)
        return b""

    def _publish(self, body: bytes, ctx) -> bytes:
        msg = pb.decode(body)
        topic = pb.first(msg, 1, b"").decode()
        out_ids = b""
        with self.state.lock:
            if topic not in self.state.topics:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
            for raw in msg.get(2, []):
                pm = pb.decode(raw)
                data = pb.first(pm, 1, b"")
                attrs = {}
                for entry in pm.get(2, []):
                    kv = pb.decode(entry)
                    attrs[pb.first(kv, 1, b"").decode()] = pb.first(kv, 2, b"").decode()
                mid = str(next(self.state.ids))
                for sub, t in self.state.subs.items():
                    if t == topic:
                        ack = f"ack-{mid}-{sub}"
                        self.state.queues.setdefault(sub, collections.deque()).append(
                            (ack, data, attrs, mid)
                        )
                out_ids += pb.str_field(1, mid)
            self.state.arrived.notify_all()  # wake StreamingPull senders
        return out_ids

    def _create_subscription(self, body: bytes, ctx) -> bytes:
        msg = pb.decode(body)
        name = pb.first(msg, 1, b"").decode()
        topic = pb.first(msg, 2, b"").decode()
        with self.state.lock:
            if name in self.state.subs:
                ctx.abort(grpc.StatusCode.ALREADY_EXISTS, "subscription exists")
            if topic not in self.state.topics:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
            self.state.subs[name] = topic
        return body

    def _delete_subscription(self, body: bytes, ctx) -> bytes:
        name = pb.first(pb.decode(body), 1, b"").decode()
        with self.state.lock:
            if name not in self.state.subs:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
            del self.state.subs[name]
            self.state.queues.pop(name, None)
        return b""

    def _pull(self, body: bytes, ctx) -> bytes:
        msg = pb.decode(body)
        sub = pb.first(msg, 1, b"").decode()
        maxn = pb.first(msg, 3, 1)
        out = b""
        with self.state.lock:
            if sub not in self.state.subs:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
            q = self.state.queues.setdefault(sub, collections.deque())
            for _ in range(min(maxn, len(q))):
                ack, data, attrs, mid = q.popleft()
                self.state.unacked[ack] = (sub, (ack, data, attrs, mid))
                pm = pb.str_field(1, data) + pb.str_field(3, mid)
                for k, v in attrs.items():
                    pm += pb.map_entry(2, k, v)
                rm = pb.str_field(1, ack) + pb.str_field(2, pm)
                out += pb.str_field(1, rm)
        return out

    def _streaming_pull(self, request_iterator, ctx):
        """Bidi StreamingPull: first request names the subscription; later
        requests carry ack_ids; responses push message batches as they
        arrive (no client round trip per message)."""
        first = pb.decode(next(request_iterator))
        sub = pb.first(first, 1, b"").decode()
        with self.state.lock:
            if sub not in self.state.subs:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")

        def ack_loop():
            try:
                for req in request_iterator:
                    msg = pb.decode(req)
                    with self.state.lock:
                        for ack in msg.get(2, []):
                            a = ack.decode()
                            self.state.unacked.pop(a, None)
                            self.state.acked.append(a)
            except Exception:  # noqa: BLE001 — stream teardown
                pass

        threading.Thread(target=ack_loop, daemon=True).start()
        while ctx.is_active():
            with self.state.lock:
                q = self.state.queues.setdefault(sub, collections.deque())
                batch = []
                while q:
                    rec = q.popleft()
                    self.state.unacked[rec[0]] = (sub, rec)
                    batch.append(rec)
                if not batch:
                    self.state.arrived.wait(timeout=0.2)
                    continue
            out = b""
            for ack, data, attrs, mid in batch:
                pm = pb.str_field(1, data) + pb.str_field(3, mid)
                for k, v in attrs.items():
                    pm += pb.map_entry(2, k, v)
                out += pb.str_field(1, pb.str_field(1, ack) + pb.str_field(2, pm))
            yield out

    def _acknowledge(self, body: bytes, ctx) -> bytes:
        msg = pb.decode(body)
        with self.state.lock:
            for ack in msg.get(2, []):
                a = ack.decode()
                self.state.unacked.pop(a, None)
                self.state.acked.append(a)
        return b""

    # test helper: redeliver everything pulled but never acked
    def redeliver_unacked(self) -> int:
        with self.state.lock:
            n = 0
            for ack, (sub, rec) in list(self.state.unacked.items()):
                self.state.queues.setdefault(sub, collections.deque()).append(rec)
                del self.state.unacked[ack]
                n += 1
            return n


class _Generic(grpc.GenericRpcHandler):
    def __init__(self, service: str, methods: dict, streams: dict | None = None):
        self._service = service
        self._methods = methods
        self._streams = streams or {}

    def service(self, handler_call_details):
        # path: /package.Service/Method
        _, svc, method = handler_call_details.method.split("/")
        if svc != self._service:
            return None
        if method in self._streams:
            fn = self._streams[method]
            return grpc.stream_stream_rpc_method_handler(
                lambda it, ctx: fn(it, ctx),
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
        if method not in self._methods:
            return None
        fn = self._methods[method]
        return grpc.unary_unary_rpc_method_handler(
            lambda body, ctx: fn(body, ctx),
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )
