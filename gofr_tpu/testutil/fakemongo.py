"""In-process MongoDB server speaking the real wire protocol.

The Mongo analogue of FakeKafkaBroker: a TCP server that parses OP_MSG
frames with the same codec the client uses (datasource/mongo/mongoproto),
executes commands against an InMemoryMongo document store, and replies in
kind. Lets WireMongo be tested end-to-end over a real socket without a
mongod — the role CI service containers play for the reference
(.github/workflows/go.yml provisions real brokers; we provision protocol-
faithful fakes).

Commands: hello, ping, find (with cursor batching + getMore), insert,
update, delete, count, drop. Error replies use real server shapes
({ok: 0, errmsg, code} and writeErrors).
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading

from ..datasource.mongo import InMemoryMongo
from ..datasource.mongo import mongoproto as mb

__all__ = ["FakeMongoServer"]


class FakeMongoServer:
    """Minimal mongod stand-in. `batch_size` forces cursor paging so the
    client's getMore path is exercised."""

    def __init__(
        self,
        batch_size: int = 101,
        users: dict[str, str] | None = None,
        tls: bool = False,
    ):
        self.store = InMemoryMongo()
        self.store.connect()
        self.batch_size = batch_size
        # users set -> connections must complete a SCRAM conversation
        # (saslStart/saslContinue) before running CRUD, like a mongod with
        # auth enabled; tls -> serve over testutil.self_signed_cert()
        self.users = users
        self.tls = tls
        self._cursors: dict[int, list[dict]] = {}
        self._cursor_ids = itertools.count(1)
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- plumbing ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.tls:
            from . import server_tls_context

            try:
                conn = server_tls_context().wrap_socket(conn, server_side=True)
            except OSError:
                return

        def recv_exact(n: int) -> bytes:
            buf = b""
            while len(buf) < n:
                chunk = conn.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError("client closed")
                buf += chunk
            return buf

        state = {"authed": self.users is None, "scram": None}
        try:
            while True:
                frame = mb.read_message(recv_exact)
                rid, _, body = mb.decode_op_msg(frame)
                try:
                    reply = self._execute(body, state)
                except _CommandError as e:
                    reply = {"ok": 0.0, "errmsg": e.args[0], "code": e.code}
                conn.sendall(
                    mb.encode_op_msg(
                        reply, request_id=next(self._cursor_ids) + 1_000_000,
                        response_to=rid,
                    )
                )
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- command dispatch --------------------------------------------------
    def _execute(self, body: dict, state: dict | None = None) -> dict:
        state = state if state is not None else {"authed": True, "scram": None}
        db = body.get("$db", "test")
        if "hello" in body or "isMaster" in body:
            return {
                "ok": 1.0, "isWritablePrimary": True,
                "maxWireVersion": 17, "minWireVersion": 0,
                "maxBsonObjectSize": 16 * 1024 * 1024,
            }
        if "ping" in body:
            return {"ok": 1.0}
        if "saslStart" in body or "saslContinue" in body:
            return self._sasl(body, state)
        if not state["authed"]:
            # mongod with auth enabled: everything else is Unauthorized
            raise _CommandError("command requires authentication", 13)
        if "find" in body:
            return self._find(db, body)
        if "getMore" in body:
            return self._get_more(db, body)
        if "insert" in body:
            return self._insert(body)
        if "update" in body:
            return self._update(body)
        if "delete" in body:
            return self._delete(body)
        if "count" in body:
            n = self.store.count_documents(body["count"], body.get("query"))
            return {"ok": 1.0, "n": n}
        if "drop" in body:
            with self._lock:
                if body["drop"] not in self.store._collections:
                    raise _CommandError("ns not found", 26)
            self.store.drop_collection(body["drop"])
            return {"ok": 1.0, "nIndexesWas": 1}
        raise _CommandError(f"no such command: {next(iter(body))!r}", 59)

    def _sasl(self, body: dict, state: dict) -> dict:
        """SCRAM conversation (saslStart/saslContinue), mongod reply
        shapes: {conversationId, payload, done, ok}."""
        import hashlib

        from ..datasource.scram import ScramError, ScramServer

        if self.users is None:
            raise _CommandError("authentication not enabled", 18)
        try:
            if "saslStart" in body:
                mech = str(body.get("mechanism", ""))
                users = self.users
                if mech == "SCRAM-SHA-1":
                    # MongoDB's SHA-1 flow uses md5(user:mongo:pwd) hex as
                    # the effective SCRAM password (drivers' auth spec)
                    users = {
                        u: hashlib.md5(f"{u}:mongo:{p}".encode()).hexdigest()
                        for u, p in users.items()
                    }
                state["scram"] = ScramServer(mech, users)
                server_first = state["scram"].process_client_first(
                    bytes(body["payload"]).decode()
                )
                return {
                    "ok": 1.0, "conversationId": 1, "done": False,
                    "payload": server_first.encode(),
                }
            if state["scram"] is None:
                raise _CommandError("no SASL conversation in progress", 17)
            payload = bytes(body.get("payload", b""))
            if not payload:  # empty final round (no skipEmptyExchange)
                return {"ok": 1.0, "conversationId": 1, "done": True,
                        "payload": b""}
            server_final = state["scram"].process_client_final(payload.decode())
            state["authed"] = True
            return {
                "ok": 1.0, "conversationId": 1, "done": True,
                "payload": server_final.encode(),
            }
        except ScramError as e:
            raise _CommandError(f"Authentication failed: {e}", 18) from e

    def _find(self, db: str, body: dict) -> dict:
        coll = body["find"]
        docs = self.store.find(coll, body.get("filter"))
        limit = int(body.get("limit", 0))
        if limit:
            docs = docs[:limit]
        first, rest = docs[: self.batch_size], docs[self.batch_size :]
        cursor_id = 0
        if rest:
            with self._lock:
                cursor_id = next(self._cursor_ids)
                self._cursors[cursor_id] = rest
        return {
            "ok": 1.0,
            "cursor": {"firstBatch": first, "id": cursor_id, "ns": f"{db}.{coll}"},
        }

    def _get_more(self, db: str, body: dict) -> dict:
        cid = body["getMore"]
        with self._lock:
            rest = self._cursors.pop(cid, None)
        if rest is None:
            raise _CommandError(f"cursor id {cid} not found", 43)
        batch, rest = rest[: self.batch_size], rest[self.batch_size :]
        new_id = 0
        if rest:
            with self._lock:
                new_id = next(self._cursor_ids)
                self._cursors[new_id] = rest
        return {
            "ok": 1.0,
            "cursor": {
                "nextBatch": batch, "id": new_id,
                "ns": f"{db}.{body['collection']}",
            },
        }

    def _insert(self, body: dict) -> dict:
        coll = body["insert"]
        n = 0
        write_errors = []
        for i, doc in enumerate(body.get("documents", [])):
            if "_id" in doc and self.store.find_one(coll, {"_id": doc["_id"]}):
                write_errors.append(
                    {"index": i, "code": 11000, "errmsg": "E11000 duplicate key"}
                )
                continue
            self.store.insert_one(coll, doc)
            n += 1
        reply = {"ok": 1.0, "n": n}
        if write_errors:
            reply["writeErrors"] = write_errors
        return reply

    def _update(self, body: dict) -> dict:
        coll = body["update"]
        n = 0
        for u in body.get("updates", []):
            q, doc, multi = u.get("q", {}), u.get("u", {}), u.get("multi", False)
            # delegate to the store's own update methods so wire and
            # in-memory backends share one query/update-semantics impl
            if multi:
                n += self.store.update_many(coll, q, doc)
            else:
                n += self.store.update_one(coll, q, doc)
        return {"ok": 1.0, "n": n, "nModified": n}

    def _delete(self, body: dict) -> dict:
        coll = body["delete"]
        n = 0
        for d in body.get("deletes", []):
            q, limit = d.get("q", {}), d.get("limit", 0)
            if limit == 1:
                n += self.store.delete_one(coll, q)
            else:
                n += self.store.delete_many(coll, q)
        return {"ok": 1.0, "n": n}


class _CommandError(Exception):
    def __init__(self, msg: str, code: int):
        super().__init__(msg)
        self.code = code
