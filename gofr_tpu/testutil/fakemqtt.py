"""In-process fake MQTT 3.1.1 broker speaking the real wire protocol.

The test stand-in for Mosquitto, exactly as FakeKafkaBroker speaks the
Kafka codec: unit tests drive the from-scratch MQTT client
(datasource/pubsub/mqtt.py) end-to-end over TCP without an external
service. Implements CONNECT/CONNACK, PUBLISH QoS 0/1 with routing to
matching subscribers (incl. '+'/'#' filters), PUBACK bookkeeping,
SUBSCRIBE/SUBACK, UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP, DISCONNECT.
"""

from __future__ import annotations

import socket
import threading

from ..datasource.pubsub import mqttproto as mp

__all__ = ["FakeMQTTBroker"]


class _Session:
    def __init__(self, conn: socket.socket, client_id: str):
        self.conn = conn
        self.client_id = client_id
        self.subs: dict[str, int] = {}  # filter -> qos
        self.wlock = threading.Lock()
        self.next_pid = 0

    def send(self, frame: bytes) -> None:
        with self.wlock:
            self.conn.sendall(frame)


class FakeMQTTBroker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        *,
        password: str | None = None,
        tls: bool = False,
    ):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self.password = password  # when set, CONNECT must carry it
        self.tls = tls  # serve over testutil.self_signed_cert()
        self._sessions: list[_Session] = []
        self._lock = threading.Lock()
        self._closed = False
        # observability for tests
        self.published: list[tuple[str, bytes, int]] = []  # (topic, payload, qos)
        self.acked: list[int] = []  # packet ids PUBACKed by subscribers
        self.connects: list[mp.ConnectInfo] = []
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for s in self._sessions:
                try:
                    s.conn.close()
                except OSError:
                    pass
            self._sessions.clear()

    def inject(self, topic: str, payload: bytes, qos: int = 0) -> None:
        """Broker-originated message delivery (tests publish without a
        second client)."""
        self._route(topic, payload, qos)

    # -- internals ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _maybe_tls(self, conn: socket.socket) -> socket.socket | None:
        """Per-connection TLS wrap (in the connection thread, like
        fakekafka — a stalled handshake must not freeze the accept loop)."""
        if not self.tls:
            return conn
        from . import server_tls_context

        try:
            return server_tls_context().wrap_socket(conn, server_side=True)
        except OSError:
            return None

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed")
            buf += chunk
        return buf

    def _serve_conn(self, conn: socket.socket) -> None:
        conn = self._maybe_tls(conn)
        if conn is None:
            return
        sess: _Session | None = None
        try:
            p = mp.read_packet_from(lambda n: self._recv_exact(conn, n))
            if p.type != mp.CONNECT:
                conn.close()
                return
            info = mp.parse_connect(p)
            self.connects.append(info)
            if self.password is not None and info.password != self.password:
                conn.sendall(mp.connack_packet(False, 5))  # not authorized
                conn.close()
                return
            sess = _Session(conn, info.client_id)
            with self._lock:
                self._sessions.append(sess)
            conn.sendall(mp.connack_packet(False, 0))
            while not self._closed:
                p = mp.read_packet_from(lambda n: self._recv_exact(conn, n))
                if p.type == mp.PUBLISH:
                    pub = mp.parse_publish(p)
                    self.published.append((pub.topic, pub.payload, pub.qos))
                    if pub.qos > 0:
                        sess.send(mp.puback_packet(pub.packet_id))
                    self._route(pub.topic, pub.payload, pub.qos)
                elif p.type == mp.SUBSCRIBE:
                    sub = mp.parse_subscribe(p)
                    for t, qos in sub.topics:
                        sess.subs[t] = min(qos, 1)
                    sess.send(
                        mp.suback_packet(sub.packet_id, [min(q, 1) for _, q in sub.topics])
                    )
                elif p.type == mp.UNSUBSCRIBE:
                    pid, topics = mp.parse_unsubscribe(p)
                    for t in topics:
                        sess.subs.pop(t, None)
                    sess.send(mp.unsuback_packet(pid))
                elif p.type == mp.PUBACK:
                    self.acked.append(mp.parse_packet_id(p))
                elif p.type == mp.PINGREQ:
                    sess.send(mp.pingresp_packet())
                elif p.type == mp.DISCONNECT:
                    break
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            if sess is not None:
                with self._lock:
                    if sess in self._sessions:
                        self._sessions.remove(sess)
            try:
                conn.close()
            except OSError:
                pass

    def _route(self, topic: str, payload: bytes, qos: int) -> None:
        with self._lock:
            sessions = list(self._sessions)
        for s in sessions:
            grant = max(
                (g for f, g in s.subs.items() if mp.topic_matches(f, topic)),
                default=None,
            )
            if grant is None:
                continue
            eff = min(qos, grant)
            if eff > 0:
                s.next_pid = s.next_pid % 65535 + 1
                frame = mp.publish_packet(topic, payload, qos=1, packet_id=s.next_pid)
            else:
                frame = mp.publish_packet(topic, payload, qos=0)
            try:
                s.send(frame)
            except OSError:
                pass
