"""In-process fake Kafka broker speaking the real wire protocol.

The test stand-in for a broker, exactly as MiniRedis (testutil) speaks real
RESP: unit tests drive the from-scratch Kafka client end-to-end over TCP
without an external service (the reference's CI instead provisions a real
Kafka container, go.yml:61-77 — this image has none, so the broker is
in-process). Implements the same API subset the client uses: Produce v2,
Fetch v2, ListOffsets v1, Metadata v1, OffsetCommit v2, OffsetFetch v1,
FindCoordinator v0, CreateTopics v0, DeleteTopics v0.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..datasource.pubsub import kafkaproto as kp

__all__ = ["FakeKafkaBroker"]


class FakeKafkaBroker:
    """Single-node broker (node_id 0). Topics live in memory as
    {topic: {partition: [Record]}}; group offsets as {(group, topic, pid)}."""

    def __init__(self, host: str = "127.0.0.1"):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self.node_id = 0
        self._topics: dict[str, dict[int, list[kp.Record]]] = {}
        self._group_offsets: dict[tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._closed = False
        # knobs for failure-injection tests
        self.fail_next_produce: int | None = None
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- message log helpers (tests assert against these) ------------------
    def records(self, topic: str, pid: int = 0) -> list[kp.Record]:
        with self._lock:
            return list(self._topics.get(topic, {}).get(pid, []))

    def committed(self, group: str, topic: str, pid: int = 0) -> int | None:
        with self._lock:
            return self._group_offsets.get((group, topic, pid))

    def seed(self, topic: str, values: list[bytes], pid: int = 0,
             partitions: int = 1) -> None:
        with self._lock:
            parts = self._topics.setdefault(
                topic, {p: [] for p in range(partitions)}
            )
            log = parts.setdefault(pid, [])
            base = len(log)
            for i, v in enumerate(values):
                log.append(kp.Record(key=None, value=v, offset=base + i))

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # -- server loop -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _recv_exact(self, conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._closed:
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                size = struct.unpack(">i", head)[0]
                payload = self._recv_exact(conn, size)
                if payload is None:
                    return
                r = kp.Reader(payload)
                api_key, _api_ver, corr = r.i16(), r.i16(), r.i32()
                r.string()  # client_id
                try:
                    body = self._dispatch(api_key, r)
                except Exception:  # noqa: BLE001 — a broken frame kills the conn
                    return
                try:
                    conn.sendall(kp.encode_response(corr, body))
                except OSError:
                    return

    def _dispatch(self, api_key: int, r: kp.Reader) -> bytes:
        if api_key == kp.METADATA:
            return self._metadata(kp.dec_metadata_req(r))
        if api_key == kp.PRODUCE:
            return self._produce(*kp.dec_produce_req(r))
        if api_key == kp.FETCH:
            return self._fetch(kp.dec_fetch_req(r))
        if api_key == kp.LIST_OFFSETS:
            return self._list_offsets(kp.dec_list_offsets_req(r))
        if api_key == kp.OFFSET_COMMIT:
            return self._offset_commit(*kp.dec_offset_commit_req(r))
        if api_key == kp.OFFSET_FETCH:
            return self._offset_fetch(*kp.dec_offset_fetch_req(r))
        if api_key == kp.FIND_COORDINATOR:
            kp.dec_find_coordinator_req(r)
            return kp.enc_find_coordinator_resp(kp.NONE, self.node_id, self.host, self.port)
        if api_key == kp.CREATE_TOPICS:
            return self._create_topics(kp.dec_create_topics_req(r))
        if api_key == kp.DELETE_TOPICS:
            return self._delete_topics(kp.dec_delete_topics_req(r))
        raise ValueError(f"unsupported api_key {api_key}")

    def _metadata(self, want: list[str] | None) -> bytes:
        with self._lock:
            names = list(self._topics) if want is None else want
            topics = []
            for name in names:
                parts = self._topics.get(name)
                if parts is None:
                    topics.append((kp.UNKNOWN_TOPIC_OR_PARTITION, name, []))
                else:
                    topics.append(
                        (kp.NONE, name, [(kp.NONE, pid, self.node_id) for pid in sorted(parts)])
                    )
        return kp.enc_metadata_resp(
            [(self.node_id, self.host, self.port)], self.node_id, topics
        )

    def _produce(self, acks: int, _timeout: int,
                 topics: dict[str, dict[int, bytes]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                for pid, record_set in parts.items():
                    if self.fail_next_produce is not None:
                        code, self.fail_next_produce = self.fail_next_produce, None
                        resp[name][pid] = (code, -1)
                        continue
                    tparts = self._topics.get(name)
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1)
                        continue
                    log = tparts[pid]
                    base = len(log)
                    for i, rec in enumerate(kp.decode_message_set(record_set)):
                        rec.offset = base + i
                        log.append(rec)
                    resp[name][pid] = (kp.NONE, base)
        return kp.enc_produce_resp(resp)

    def _fetch(self, topics: dict[str, dict[int, tuple[int, int]]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int, bytes]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                tparts = self._topics.get(name)
                for pid, (offset, max_bytes) in parts.items():
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1, b"")
                        continue
                    log = tparts[pid]
                    hw = len(log)
                    if offset > hw:
                        resp[name][pid] = (kp.OFFSET_OUT_OF_RANGE, hw, b"")
                        continue
                    out, size = [], 0
                    for rec in log[offset:]:
                        out.append(rec)
                        # value=None is a tombstone (0 payload bytes on wire)
                        size += len(rec.value or b"") + 34
                        if size >= max_bytes:
                            break
                    resp[name][pid] = (kp.NONE, hw, kp.encode_message_set(out))
        return kp.enc_fetch_resp(resp)

    def _list_offsets(self, topics: dict[str, dict[int, int]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                tparts = self._topics.get(name)
                for pid, ts in parts.items():
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1)
                    elif ts == kp.EARLIEST:
                        resp[name][pid] = (kp.NONE, 0)
                    else:  # LATEST
                        resp[name][pid] = (kp.NONE, len(tparts[pid]))
        return kp.enc_list_offsets_resp(resp)

    def _offset_commit(self, group: str,
                       topics: dict[str, dict[int, int]]) -> bytes:
        resp: dict[str, dict[int, int]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                for pid, off in parts.items():
                    self._group_offsets[(group, name, pid)] = off
                    resp[name][pid] = kp.NONE
        return kp.enc_offset_commit_resp(resp)

    def _offset_fetch(self, group: str, topics: dict[str, list[int]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, pids in topics.items():
                resp[name] = {
                    pid: (self._group_offsets.get((group, name, pid), -1), kp.NONE)
                    for pid in pids
                }
        return kp.enc_offset_fetch_resp(resp)

    def _create_topics(self, topics: dict[str, int]) -> bytes:
        resp: dict[str, int] = {}
        with self._lock:
            for name, nparts in topics.items():
                if name in self._topics:
                    resp[name] = kp.TOPIC_ALREADY_EXISTS
                else:
                    self._topics[name] = {p: [] for p in range(max(1, nparts))}
                    resp[name] = kp.NONE
        return kp.enc_create_topics_resp(resp)

    def _delete_topics(self, topics: list[str]) -> bytes:
        resp: dict[str, int] = {}
        with self._lock:
            for name in topics:
                if name in self._topics:
                    del self._topics[name]
                    resp[name] = kp.NONE
                else:
                    resp[name] = kp.UNKNOWN_TOPIC_OR_PARTITION
        return kp.enc_delete_topics_resp(resp)
