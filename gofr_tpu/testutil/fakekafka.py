"""In-process fake Kafka broker speaking the real wire protocol.

The test stand-in for a broker, exactly as MiniRedis (testutil) speaks real
RESP: unit tests drive the from-scratch Kafka client end-to-end over TCP
without an external service (the reference's CI instead provisions a real
Kafka container, go.yml:61-77 — this image has none, so the broker is
in-process). Implements the same API subset the client uses: Produce v2/v3,
Fetch v2/v4 (v2 record batches AND v1 message sets), ListOffsets v1,
Metadata v1, OffsetCommit v2, OffsetFetch v1, FindCoordinator v0,
CreateTopics v0, DeleteTopics v0, ApiVersions v0, SaslHandshake v1,
SaslAuthenticate v0 (PLAIN + SCRAM). `legacy=True` advertises only the
old Produce/Fetch versions so tests cover the v1 MessageSet negotiation
path; `users=` enforces SASL; `tls=True` serves the self-signed test cert.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..datasource.pubsub import kafkaproto as kp

__all__ = ["FakeKafkaBroker"]


class FakeKafkaBroker:
    """Single-node broker (node_id 0). Topics live in memory as
    {topic: {partition: [Record]}}; group offsets as {(group, topic, pid)}."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        *,
        legacy: bool = False,
        users: dict[str, str] | None = None,
        tls: bool = False,
    ):
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self.node_id = 0
        self.legacy = legacy  # advertise pre-KIP-98 Produce/Fetch only
        self.users = users  # SASL required when set
        self.tls = tls
        self._topics: dict[str, dict[int, list[kp.Record]]] = {}
        self._group_offsets: dict[tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._closed = False
        # knobs for failure-injection tests
        self.fail_next_produce: int | None = None
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def api_versions(self) -> dict[int, tuple[int, int]]:
        produce_fetch = {
            kp.PRODUCE: (0, 2), kp.FETCH: (0, 2),
        } if self.legacy else {
            kp.PRODUCE: (0, 3), kp.FETCH: (0, 4),
        }
        return {
            **produce_fetch,
            kp.LIST_OFFSETS: (0, 1), kp.METADATA: (0, 1),
            kp.OFFSET_COMMIT: (0, 2), kp.OFFSET_FETCH: (0, 1),
            kp.FIND_COORDINATOR: (0, 0), kp.SASL_HANDSHAKE: (0, 1),
            kp.API_VERSIONS: (0, 0), kp.CREATE_TOPICS: (0, 0),
            kp.DELETE_TOPICS: (0, 0), kp.SASL_AUTHENTICATE: (0, 0),
        }

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- message log helpers (tests assert against these) ------------------
    def records(self, topic: str, pid: int = 0) -> list[kp.Record]:
        with self._lock:
            return list(self._topics.get(topic, {}).get(pid, []))

    def committed(self, group: str, topic: str, pid: int = 0) -> int | None:
        with self._lock:
            return self._group_offsets.get((group, topic, pid))

    def seed(self, topic: str, values: list[bytes], pid: int = 0,
             partitions: int = 1) -> None:
        with self._lock:
            parts = self._topics.setdefault(
                topic, {p: [] for p in range(partitions)}
            )
            log = parts.setdefault(pid, [])
            base = len(log)
            for i, v in enumerate(values):
                log.append(kp.Record(key=None, value=v, offset=base + i))

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # -- server loop -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _recv_exact(self, conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _serve_conn(self, conn: socket.socket) -> None:
        if self.tls:
            from . import server_tls_context

            try:
                conn = server_tls_context().wrap_socket(conn, server_side=True)
            except OSError:
                return
        # per-connection SASL state, like a real broker's SASL listener
        state = {"authed": self.users is None, "scram": None, "mech": None}
        with conn:
            while not self._closed:
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                size = struct.unpack(">i", head)[0]
                payload = self._recv_exact(conn, size)
                if payload is None:
                    return
                r = kp.Reader(payload)
                api_key, api_ver, corr = r.i16(), r.i16(), r.i32()
                r.string()  # client_id
                if not state["authed"] and api_key not in (
                    kp.API_VERSIONS, kp.SASL_HANDSHAKE, kp.SASL_AUTHENTICATE
                ):
                    return  # real brokers cut unauthenticated connections
                try:
                    body = self._dispatch(api_key, api_ver, r, state)
                except Exception:  # noqa: BLE001 — a broken frame kills the conn
                    return
                try:
                    conn.sendall(kp.encode_response(corr, body))
                except OSError:
                    return

    def _dispatch(self, api_key: int, api_ver: int, r: kp.Reader, state: dict) -> bytes:
        if api_key == kp.API_VERSIONS:
            return kp.enc_api_versions_resp(self.api_versions())
        if api_key == kp.SASL_HANDSHAKE:
            return self._sasl_handshake(kp.dec_sasl_handshake_req(r), state)
        if api_key == kp.SASL_AUTHENTICATE:
            return self._sasl_authenticate(kp.dec_sasl_authenticate_req(r), state)
        if api_key == kp.METADATA:
            return self._metadata(kp.dec_metadata_req(r))
        if api_key == kp.PRODUCE:
            if api_ver >= 3:
                return self._produce(*kp.dec_produce_req_v3(r), api_ver=api_ver)
            return self._produce(*kp.dec_produce_req(r), api_ver=api_ver)
        if api_key == kp.FETCH:
            if api_ver >= 4:
                return self._fetch(kp.dec_fetch_req_v4(r), api_ver=api_ver)
            return self._fetch(kp.dec_fetch_req(r), api_ver=api_ver)
        if api_key == kp.LIST_OFFSETS:
            return self._list_offsets(kp.dec_list_offsets_req(r))
        if api_key == kp.OFFSET_COMMIT:
            return self._offset_commit(*kp.dec_offset_commit_req(r))
        if api_key == kp.OFFSET_FETCH:
            return self._offset_fetch(*kp.dec_offset_fetch_req(r))
        if api_key == kp.FIND_COORDINATOR:
            kp.dec_find_coordinator_req(r)
            return kp.enc_find_coordinator_resp(kp.NONE, self.node_id, self.host, self.port)
        if api_key == kp.CREATE_TOPICS:
            return self._create_topics(kp.dec_create_topics_req(r))
        if api_key == kp.DELETE_TOPICS:
            return self._delete_topics(kp.dec_delete_topics_req(r))
        raise ValueError(f"unsupported api_key {api_key}")

    def _sasl_handshake(self, mechanism: str, state: dict) -> bytes:
        # what real brokers offer (no SHA-1 in Kafka)
        offered = ["PLAIN", "SCRAM-SHA-256", "SCRAM-SHA-512"]
        if self.users is None or mechanism not in offered:
            return kp.enc_sasl_handshake_resp(
                kp.UNSUPPORTED_SASL_MECHANISM, offered
            )
        state["mech"] = mechanism
        return kp.enc_sasl_handshake_resp(kp.NONE, offered)

    def _sasl_authenticate(self, auth: bytes, state: dict) -> bytes:
        from ..datasource.scram import ScramError, ScramServer

        mech = state.get("mech")
        if mech is None:
            return kp.enc_sasl_authenticate_resp(
                kp.ILLEGAL_SASL_STATE, "handshake first", b""
            )
        if mech == "PLAIN":
            try:
                _authzid, user, password = auth.split(b"\x00", 2)
            except ValueError:
                return kp.enc_sasl_authenticate_resp(
                    kp.SASL_AUTHENTICATION_FAILED, "malformed PLAIN", b""
                )
            if self.users.get(user.decode()) == password.decode():
                state["authed"] = True
                return kp.enc_sasl_authenticate_resp(kp.NONE, None, b"")
            return kp.enc_sasl_authenticate_resp(
                kp.SASL_AUTHENTICATION_FAILED, "bad credentials", b""
            )
        try:
            if state["scram"] is None:
                state["scram"] = ScramServer(mech, self.users)
                first = state["scram"].process_client_first(auth.decode())
                return kp.enc_sasl_authenticate_resp(kp.NONE, None, first.encode())
            final = state["scram"].process_client_final(auth.decode())
            state["authed"] = True
            state["scram"] = None
            return kp.enc_sasl_authenticate_resp(kp.NONE, None, final.encode())
        except ScramError as e:
            state["scram"] = None
            return kp.enc_sasl_authenticate_resp(
                kp.SASL_AUTHENTICATION_FAILED, str(e), b""
            )

    def _metadata(self, want: list[str] | None) -> bytes:
        with self._lock:
            names = list(self._topics) if want is None else want
            topics = []
            for name in names:
                parts = self._topics.get(name)
                if parts is None:
                    topics.append((kp.UNKNOWN_TOPIC_OR_PARTITION, name, []))
                else:
                    topics.append(
                        (kp.NONE, name, [(kp.NONE, pid, self.node_id) for pid in sorted(parts)])
                    )
        return kp.enc_metadata_resp(
            [(self.node_id, self.host, self.port)], self.node_id, topics
        )

    def _produce(self, acks: int, _timeout: int,
                 topics: dict[str, dict[int, bytes]], api_ver: int = 2) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                for pid, record_set in parts.items():
                    if self.fail_next_produce is not None:
                        code, self.fail_next_produce = self.fail_next_produce, None
                        resp[name][pid] = (code, -1)
                        continue
                    tparts = self._topics.get(name)
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1)
                        continue
                    log = tparts[pid]
                    base = len(log)
                    # decode_records sniffs v1 MessageSet vs v2 batch, so
                    # one store serves clients on either format
                    for i, rec in enumerate(kp.decode_records(record_set)):
                        rec.offset = base + i
                        log.append(rec)
                    resp[name][pid] = (kp.NONE, base)
        return kp.enc_produce_resp(resp)

    def _fetch(self, topics: dict[str, dict[int, tuple[int, int]]],
               api_ver: int = 2) -> bytes:
        resp: dict[str, dict[int, tuple[int, int, bytes]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                tparts = self._topics.get(name)
                for pid, (offset, max_bytes) in parts.items():
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1, b"")
                        continue
                    log = tparts[pid]
                    hw = len(log)
                    if offset > hw:
                        resp[name][pid] = (kp.OFFSET_OUT_OF_RANGE, hw, b"")
                        continue
                    out, size = [], 0
                    for rec in log[offset:]:
                        out.append(rec)
                        # value=None is a tombstone (0 payload bytes on wire)
                        size += len(rec.value or b"") + 34
                        if size >= max_bytes:
                            break
                    if not out:
                        wire = b""
                    elif api_ver >= 4:
                        # v2 batch offsets are base+delta: rebase on the
                        # first record's absolute offset
                        wire = kp.encode_record_batch(out, base_offset=out[0].offset)
                    else:
                        wire = kp.encode_message_set(out)
                    resp[name][pid] = (kp.NONE, hw, wire)
        if api_ver >= 4:
            return kp.enc_fetch_resp_v4(resp)
        return kp.enc_fetch_resp(resp)

    def _list_offsets(self, topics: dict[str, dict[int, int]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                tparts = self._topics.get(name)
                for pid, ts in parts.items():
                    if tparts is None or pid not in tparts:
                        resp[name][pid] = (kp.UNKNOWN_TOPIC_OR_PARTITION, -1)
                    elif ts == kp.EARLIEST:
                        resp[name][pid] = (kp.NONE, 0)
                    else:  # LATEST
                        resp[name][pid] = (kp.NONE, len(tparts[pid]))
        return kp.enc_list_offsets_resp(resp)

    def _offset_commit(self, group: str,
                       topics: dict[str, dict[int, int]]) -> bytes:
        resp: dict[str, dict[int, int]] = {}
        with self._lock:
            for name, parts in topics.items():
                resp[name] = {}
                for pid, off in parts.items():
                    self._group_offsets[(group, name, pid)] = off
                    resp[name][pid] = kp.NONE
        return kp.enc_offset_commit_resp(resp)

    def _offset_fetch(self, group: str, topics: dict[str, list[int]]) -> bytes:
        resp: dict[str, dict[int, tuple[int, int]]] = {}
        with self._lock:
            for name, pids in topics.items():
                resp[name] = {
                    pid: (self._group_offsets.get((group, name, pid), -1), kp.NONE)
                    for pid in pids
                }
        return kp.enc_offset_fetch_resp(resp)

    def _create_topics(self, topics: dict[str, int]) -> bytes:
        resp: dict[str, int] = {}
        with self._lock:
            for name, nparts in topics.items():
                if name in self._topics:
                    resp[name] = kp.TOPIC_ALREADY_EXISTS
                else:
                    self._topics[name] = {p: [] for p in range(max(1, nparts))}
                    resp[name] = kp.NONE
        return kp.enc_create_topics_resp(resp)

    def _delete_topics(self, topics: list[str]) -> bytes:
        resp: dict[str, int] = {}
        with self._lock:
            for name in topics:
                if name in self._topics:
                    del self._topics[name]
                    resp[name] = kp.NONE
                else:
                    resp[name] = kp.UNKNOWN_TOPIC_OR_PARTITION
        return kp.enc_delete_topics_resp(resp)
