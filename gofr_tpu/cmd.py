"""CLI app mode: subcommand dispatch with the same Context as HTTP handlers.

Parity: reference pkg/gofr/cmd.go:27-65 (NewCMD builds an app without
servers; Run joins non-flag args into a command string and regex-matches
registered subcommand patterns) and pkg/gofr/cmd/ (request.go:25-117 flag
parsing ``-a=b``/``--flag`` into params, reflection Bind; Responder prints
results to stdout, errors to stderr).
"""

from __future__ import annotations

import dataclasses
import re
import sys
from typing import Any, Callable, get_type_hints

from .config import Config, EnvConfig
from .container import Container
from .context import Context


class CMDRequest:
    """Parses argv: non-flag words form the command; -k=v / --k=v / -flag
    become params (cmd/request.go:25-117)."""

    def __init__(self, argv: list[str]):
        self.params: dict[str, str] = {}
        words: list[str] = []
        for arg in argv:
            if arg.startswith("-"):
                key = arg.lstrip("-")
                if "=" in key:
                    k, _, v = key.partition("=")
                    self.params[k] = v
                elif key:
                    self.params[key] = "true"
            else:
                words.append(arg)
        self.command = " ".join(words)
        self.context: dict[str, Any] = {}

    def param(self, key: str) -> str:
        return self.params.get(key, "")

    def params_list(self, key: str) -> list[str]:
        v = self.params.get(key)
        return [v] if v is not None else []

    # Context delegation surface
    def path_param(self, key: str) -> str:
        return self.params.get(key, "")

    def header(self, _key: str) -> str:
        return ""

    def host_name(self) -> str:
        return ""

    def bind(self, target: Any = None) -> Any:
        """Bind flags onto a dataclass by field name (cmd Bind analogue)."""
        if target is None:
            return dict(self.params)
        if dataclasses.is_dataclass(target):
            hints = get_type_hints(target)
            kwargs = {}
            for f in dataclasses.fields(target):
                if f.name in self.params:
                    v: Any = self.params[f.name]
                    t = hints.get(f.name, str)
                    if t is int:
                        v = int(v)
                    elif t is float:
                        v = float(v)
                    elif t is bool:
                        v = str(v).lower() in ("1", "true", "yes", "on")
                    kwargs[f.name] = v
            return target(**kwargs)
        raise TypeError("bind target must be a dataclass or None")


def profile_command(ctx: Context) -> str:
    """Built-in `profile` subcommand: run one jax.profiler capture window
    and report where the trace landed. Flags: -seconds=N (default 2,
    clamped 0.1..30), -dir=PATH (trace dir; default GOFR_PROFILE_DIR or
    the tmpdir), -out=FILE.zip (also write the zipped archive there).
    Parks with mode=fallback where the profiler is unavailable — the
    archive then carries the park reason instead of a device trace."""
    from .profiling.capture import profiler_capture

    seconds = float(ctx.param("seconds") or 2.0)
    trace_dir = ctx.param("dir") or None
    res = profiler_capture().capture(seconds, trace_dir=trace_dir)
    out = ctx.param("out")
    if out:
        with open(out, "wb") as f:
            f.write(res["archive"])
    parked = f" (parked: {res['parked']})" if res.get("parked") else ""
    return (
        f"profile mode={res['mode']}{parked} seconds={res['seconds']} "
        f"files={len(res['files'])} dir={res['dir']}"
        + (f" archive={out}" if out else "")
    )


def replay_command(ctx: Context) -> str:
    """Built-in `replay` subcommand: the record/replay loop's CLI face
    (gofr_tpu.flightrec; docs/advanced-guide/incident-debugging.md).

    ``replay -id=N`` POSTs the serving process's loopback-only
    /.well-known/debug/replay route — the engine re-executes flight
    record N with pinned version/adapter/grammar/seed and reports the
    first-divergence token index vs the recorded emission. Flags:
    -id=N (required), -url=http://127.0.0.1:9100 (default), -model=NAME
    (searches all models when omitted), -timeout=SECONDS (default 120).

    ``replay -bundle=DIR`` instead lists the flight records inside a
    black-box bundle directory on disk — the "which id do I replay"
    step of the incident runbook."""
    import json as _json
    import os as _os_mod
    import urllib.request

    bundle = ctx.param("bundle")
    if bundle:
        path = _os_mod.path.join(bundle, "flight_records.json")
        with open(path) as f:
            records = _json.load(f)
        lines = [f"{len(records)} flight record(s) in {bundle}:"]
        for r in records:
            lines.append(
                f"  id={r.get('id')} model={r.get('model')}"
                f"@{r.get('model_version')} "
                f"prompt={r.get('prompt_len')} "
                f"emitted={r.get('emitted_len')} "
                f"finish={r.get('finish_reason')} "
                f"{'final' if r.get('final') else 'IN-FLIGHT'}"
                f"{' redacted' if r.get('redacted') else ''}"
            )
        return "\n".join(lines)
    rid = ctx.param("id")
    if not rid:
        raise ValueError("replay needs -id=N (or -bundle=DIR to list one)")
    url = ctx.param("url") or "http://127.0.0.1:9100"
    body: dict[str, Any] = {"id": int(rid)}
    if ctx.param("model"):
        body["model"] = ctx.param("model")
    timeout = float(ctx.param("timeout") or 120.0)
    body["timeout"] = timeout
    req = urllib.request.Request(
        f"{url}/.well-known/debug/replay",
        data=_json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout + 30.0) as resp:
        out = _json.loads(resp.read())
    data = out.get("data", out) if isinstance(out, dict) else {}
    rep = data.get("replay", {})
    if rep.get("error"):
        raise ValueError(f"replay failed: {rep['error']}")
    div = rep.get("first_divergence")
    verdict = (
        "token-identical" if rep.get("match")
        else f"DIVERGED at token index {div}"
    )
    return (
        f"replay id={rep.get('id')} model={data.get('model')}"
        f"@{rep.get('model_version')} {verdict} "
        f"(recorded {rep.get('recorded_len')} tokens, replayed "
        f"{rep.get('replayed_len')}, {rep.get('replay_ms')} ms)"
    )


class CMDApp:
    """App without servers; run() dispatches one subcommand (cmd.go:27-52)."""

    def __init__(self, config: Config | None = None, configs_dir: str = "./configs"):
        self.config = config if config is not None else EnvConfig(configs_dir)
        self.container = Container.create(self.config)
        self.logger = self.container.logger
        self._routes: list[tuple[re.Pattern, Callable, str]] = []
        # Built-in subcommands, the CLI face of the profiler endpoint
        # (GoFr ships pprof on by default; we ship the XLA capture).
        # Dispatched AFTER user routes and anchored with \Z, so neither a
        # user's own `profile` command nor a `profile-export`-style name
        # is ever hijacked by the builtin.
        self._builtins: list[tuple[re.Pattern, Callable, str]] = [(
            re.compile(r"profile\Z"),
            profile_command,
            "capture a device profile (-seconds=N -dir=PATH -out=FILE.zip)",
        ), (
            re.compile(r"replay\Z"),
            replay_command,
            "deterministically replay a flight record "
            "(-id=N [-url=... -model=... -timeout=S] | -bundle=DIR)",
        )]

    def sub_command(self, pattern: str, handler: Callable, description: str = "") -> None:
        """Register a subcommand; pattern is a regex matched against the
        joined non-flag args (gofr.go:277, cmd.go:56-65)."""
        self._routes.append((re.compile(pattern), handler, description))

    # alias matching the reference's SubCommand naming
    add_sub_command = sub_command

    def _help_text(self) -> str:
        lines = ["Available commands:"]
        for pat, _, desc in self._routes + self._builtins:
            lines.append(f"  {pat.pattern}  {('- ' + desc) if desc else ''}")
        return "\n".join(lines)

    def run(self, argv: list[str] | None = None) -> int:
        argv = argv if argv is not None else sys.argv[1:]
        req = CMDRequest(argv)
        if not req.command or req.command in ("help", "--help"):
            print(self._help_text())
            return 0
        for pattern, handler, _desc in self._routes + self._builtins:
            if pattern.fullmatch(req.command) or pattern.match(req.command):
                ctx = Context(req, self.container)
                try:
                    result = handler(ctx)
                except Exception as e:  # noqa: BLE001 - CLI error boundary
                    print(str(e) or e.__class__.__name__, file=sys.stderr)
                    return 1
                if result is not None:
                    print(result)
                return 0
        print(f"No Command Found! {req.command!r}", file=sys.stderr)
        print(self._help_text(), file=sys.stderr)
        return 1
