"""Configuration: env-file loading with environment-specific overrides.

Parity: reference pkg/gofr/config/ (config.go:3-6 Config interface;
godotenv.go:10-77 loader semantics: load ./configs/.env, then override with
.local.env or .{APP_ENV}.env, process environment always wins).
"""

from __future__ import annotations

import os
from typing import Mapping


class Config:
    """Read-only config facade: get / get_or_default."""

    def get(self, key: str) -> str | None:
        raise NotImplementedError

    def get_or_default(self, key: str, default: str) -> str:
        v = self.get(key)
        return v if v not in (None, "") else default

    # Typed helpers (the reference parses ints inline at each call site;
    # centralizing avoids repeated try/except blocks).
    def get_int(self, key: str, default: int) -> int:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return int(v)  # type: ignore[arg-type]
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        v = self.get(key)
        if v in (None, ""):
            return default
        try:
            return float(v)  # type: ignore[arg-type]
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v in (None, ""):
            return default
        return str(v).strip().lower() in ("1", "true", "yes", "on")


def _parse_env_file(path: str) -> dict[str, str]:
    out: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("export "):
                    line = line[len("export ") :]
                if "=" not in line:
                    continue
                k, _, v = line.partition("=")
                k, v = k.strip(), v.strip()
                if len(v) >= 2 and v[0] == v[-1] and v[0] in ("'", '"'):
                    v = v[1:-1]
                out[k] = v
    except FileNotFoundError:
        pass
    return out


class EnvConfig(Config):
    """Layered env config.

    Precedence (highest wins): process env > .{APP_ENV}.env / .local.env >
    .env. Matches reference config/godotenv.go:33-66.
    """

    def __init__(self, configs_dir: str = "./configs", environ: Mapping[str, str] | None = None):
        self._environ = environ if environ is not None else os.environ
        base = _parse_env_file(os.path.join(configs_dir, ".env"))
        app_env = self._environ.get("APP_ENV", "") or base.get("APP_ENV", "")
        override_file = f".{app_env}.env" if app_env else ".local.env"
        override = _parse_env_file(os.path.join(configs_dir, override_file))
        self._values = {**base, **override}

    def get(self, key: str) -> str | None:
        if key in self._environ:
            return self._environ[key]
        return self._values.get(key)


class MapConfig(Config):
    """Dict-backed config for tests. Parity: config/mock_config.go:7."""

    def __init__(self, values: dict[str, str] | None = None):
        self._values = dict(values or {})

    def get(self, key: str) -> str | None:
        return self._values.get(key)

    def set(self, key: str, value: str) -> None:
        self._values[key] = value


def new_mock_config(values: dict[str, str] | None = None) -> MapConfig:
    return MapConfig(values)
