"""Analytic model FLOPs, device peaks, and MFU/roofline classification.

MFU (model FLOPs utilization, the PaLM-system-report framing) is the
serving health signal the latency histograms cannot give: *useful* model
FLOPs per second divided by the chip's peak. The analytic side is
computed ONCE per registered model from the architecture — the standard
2·params·tokens matmul count plus the attention correction (4·L·H·d per
token per attended position, QKᵀ and AV) — and the engine combines it
with measured phase wall time per prefill wave / decode chunk.

Roofline classification compares the program's compute time at peak
FLOPs against its memory time at peak HBM bandwidth: decode streams the
whole weight set plus the live KV prefix per step, so it is
memory-bound everywhere that matters; prefill at real batch widths is
compute-bound. A phase whose measured ratio flips side is the first
sign a kernel regressed.

Peaks are tabulated per TPU device kind (bf16 dense MXU numbers, the
convention MFU reports use even when serving int8). Off-TPU there is no
honest peak: the CPU backend uses a nominal 1 TFLOP/s placeholder so
the gauges stay finite and testable — override with the
``TPU_PEAK_FLOPS`` / ``TPU_HBM_BW`` env knobs when you care about the
absolute value.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "ModelCosts",
    "model_costs",
    "decode_flops",
    "prefill_flops",
    "chunk_prefill_flops",
    "spec_verify_flops",
    "device_peak_flops",
    "device_hbm_bandwidth",
    "roofline_ratio",
    "classify_bound",
]

# bf16 dense peak FLOP/s and HBM bandwidth (B/s) by device-kind substring.
# v5e numbers match bench.py's V5E_PEAK_BF16 / V5E_HBM_BW constants.
_TPU_PEAKS: tuple[tuple[str, float, float], ...] = (
    ("v5 lite", 197e12, 8.2e11),
    ("v5e", 197e12, 8.2e11),
    ("v5p", 459e12, 2.765e12),
    ("v6 lite", 918e12, 1.64e12),
    ("v6e", 918e12, 1.64e12),
    ("v4", 275e12, 1.2e12),
    ("v3", 123e12, 9.0e11),
    ("v2", 45e12, 7.0e11),
)

# Off-TPU placeholder peak: keeps MFU/roofline math finite on the CPU
# test backend without pretending to know the host's real roofline.
_FALLBACK_PEAK_FLOPS = 1e12
_FALLBACK_HBM_BW = 1e11


def device_peak_flops(platform: str = "", device_kind: str = "") -> float:
    """Peak dense FLOP/s per chip (bf16 convention). TPU_PEAK_FLOPS
    overrides; unknown device kinds fall back to the nominal placeholder."""
    env = os.environ.get("TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    if platform == "tpu" or "tpu" in kind:
        for sub, peak, _bw in _TPU_PEAKS:
            if sub in kind:
                return peak
    return _FALLBACK_PEAK_FLOPS


def device_hbm_bandwidth(platform: str = "", device_kind: str = "") -> float:
    """Peak HBM bandwidth per chip in B/s (TPU_HBM_BW overrides)."""
    env = os.environ.get("TPU_HBM_BW")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    if platform == "tpu" or "tpu" in kind:
        for sub, _peak, bw in _TPU_PEAKS:
            if sub in kind:
                return bw
    return _FALLBACK_HBM_BW


@dataclass(frozen=True)
class ModelCosts:
    """Per-model analytic constants, computed once at engine registration.

    ``matmul_flops_per_token`` is the classic 2·params count over the
    weight matmuls a decoded token touches (layer stack + the unembed
    projection; the embedding *lookup* is a gather, not a matmul).
    ``attn_flops_per_token_per_ctx`` is the attention correction per
    attended position: QKᵀ and AV are each 2·H·d FLOPs per (token,
    position) pair per layer."""

    params: int  # total parameter count (embed counted once when tied)
    layer_params: int  # weight params across the layer stack
    embed_params: int  # vocab x d_model (the unembed matmul's matrix)
    matmul_flops_per_token: int
    attn_flops_per_token_per_ctx: int
    kv_bytes_per_ctx_token: int  # bytes of K+V a step reads per attended position
    params_bytes: int  # resident weight bytes (int8 when quantized)
    sliding_window: int  # 0 = global attention


def model_costs(cfg, *, quantized: bool = False) -> ModelCosts:
    """Architecture-derived cost constants for a TransformerConfig.

    Matches the parameter accounting bench.py's raw probes use (attention
    projections with GQA, the 3-matrix gated MLP, one vocab x d embed
    matrix) so the two never disagree about what "2·params" means."""
    layer_params = (
        cfg.d_model * (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_dim  # qkv
        + cfg.n_heads * cfg.head_dim * cfg.d_model  # attention out
        + 3 * cfg.d_model * cfg.d_ff  # gate/up/down
    ) * cfg.n_layers
    embed_params = cfg.vocab_size * cfg.d_model
    itemsize = 1 if quantized else _dtype_itemsize(cfg.dtype)
    kv_itemsize = _dtype_itemsize(cfg.dtype)  # KV cache stays cfg.dtype
    return ModelCosts(
        params=layer_params + embed_params,
        layer_params=layer_params,
        embed_params=embed_params,
        matmul_flops_per_token=2 * (layer_params + embed_params),
        attn_flops_per_token_per_ctx=4 * cfg.n_layers * cfg.n_heads * cfg.head_dim,
        kv_bytes_per_ctx_token=2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * kv_itemsize,
        params_bytes=(layer_params + embed_params) * itemsize,
        sliding_window=int(getattr(cfg, "sliding_window", 0) or 0),
    )


def _dtype_itemsize(dtype) -> int:
    try:
        import numpy as np

        return int(np.dtype(dtype).itemsize)
    except Exception:  # noqa: BLE001 — bf16 has no numpy dtype pre-ml_dtypes
        name = str(getattr(dtype, "__name__", dtype))
        return 2 if "16" in name else 4


def decode_flops(costs: ModelCosts, tokens: int, ctx_total: int) -> float:
    """FLOPs for `tokens` decoded tokens attending over `ctx_total`
    summed context positions (already window-capped by the caller)."""
    return (
        tokens * costs.matmul_flops_per_token
        + costs.attn_flops_per_token_per_ctx * ctx_total
    )


def prefill_flops(costs: ModelCosts, seq_lens: list[int]) -> float:
    """FLOPs for one prefill wave over the given actual prompt lengths.
    Useful-work convention: padding rows and pad tail positions count
    zero, so MFU reads as useful model FLOPs per peak — padding waste
    shows up as LOW utilization rather than being flattered away. The
    unembed matmul runs once per sequence (last position only) and
    causal attention attends ~s/2 positions per token (window-capped)."""
    total = 0.0
    w = costs.sliding_window
    for s in seq_lens:
        if not w or s <= w:
            attended = s * (s + 1) / 2  # full causal triangle
        else:
            # exact window cap: the first w tokens attend causally, every
            # later token attends exactly w positions
            attended = w * (w + 1) / 2 + (s - w) * w
        total += (
            2 * s * costs.layer_params
            + 2 * costs.embed_params
            + costs.attn_flops_per_token_per_ctx * attended
        )
    return total


def chunk_prefill_flops(costs: ModelCosts, spans: list[tuple[int, int]]) -> float:
    """FLOPs for one chunked-prefill step over `spans` of (cursor, n_new):
    n_new tokens appended at absolute positions [cursor, cursor + n_new).
    Same useful-work convention as prefill_flops (padding lanes count
    zero), but attention is position-exact — token at position p attends
    min(p + 1, window) keys — and the unembed matmul bills once per span
    (the step op computes last-token logits every chunk, which is the
    chunked path's extra cost over one-shot prefill)."""
    total = 0.0
    w = costs.sliding_window

    def attended_below(p: int) -> float:
        # sum over positions 0..p-1 of min(pos + 1, window or inf)
        if not w or p <= w:
            return p * (p + 1) / 2
        return w * (w + 1) / 2 + (p - w) * w

    for cursor, n in spans:
        if n <= 0:
            continue
        attended = attended_below(cursor + n) - attended_below(cursor)
        total += (
            2 * n * costs.layer_params
            + 2 * costs.embed_params
            + costs.attn_flops_per_token_per_ctx * attended
        )
    return total


def spec_verify_flops(costs: ModelCosts, spans: list[tuple[int, int]]) -> float:
    """USEFUL FLOPs for one speculative-decoding verify step over `spans`
    of (cursor, n_emitted): the tokens the step actually produced —
    accepted draft tokens plus the bonus token per lane.

    The useful-work convention, applied to speculation: a verify
    forward pass computes draft+1 positions per lane but only
    n_emitted of them advanced the stream, so VERIFIED-BUT-REJECTED
    positions bill ZERO here — exactly like padding rows in
    prefill_flops. MFU (useful FLOPs / wall / peak) then reads LOW when
    acceptance is poor instead of being flattered by throwaway compute,
    which is the honest signal: a spec engine at 0% acceptance burns
    the wall of a (draft+1)-wide pass for one token of progress.
    Per-token accounting matches decode_flops (full matmul stack +
    unembed per emitted token — every emitted token's position WAS
    sampled from its own unembed) with position-exact attention per
    accepted position, the chunk_prefill_flops span convention."""
    total = 0.0
    w = costs.sliding_window

    def attended_below(p: int) -> float:
        if not w or p <= w:
            return p * (p + 1) / 2
        return w * (w + 1) / 2 + (p - w) * w

    for cursor, n in spans:
        if n <= 0:
            continue
        attended = attended_below(cursor + n) - attended_below(cursor)
        total += (
            n * costs.matmul_flops_per_token
            + costs.attn_flops_per_token_per_ctx * attended
        )
    return total


def roofline_ratio(flops: float, bytes_moved: float, peak_flops: float, hbm_bw: float) -> float:
    """compute_time / memory_time for one program execution: > 1 means
    the roofline predicts compute-bound, < 1 memory(HBM)-bound."""
    if bytes_moved <= 0 or peak_flops <= 0 or hbm_bw <= 0:
        return 0.0
    return (flops / peak_flops) / (bytes_moved / hbm_bw)


def classify_bound(ratio: float) -> str:
    if ratio <= 0:
        return "unknown"
    return "compute" if ratio >= 1.0 else "memory"
