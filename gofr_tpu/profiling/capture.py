"""On-demand device profiler capture with single-capture concurrency.

The analogue of GoFr exposing pprof next to its metrics server: a
running gofr_tpu server can hand back a device profile without a
restart. ``POST /.well-known/debug/profile`` (and the ``profile`` CLI
subcommand) drive :class:`ProfilerCapture`, which runs
``jax.profiler.start_trace``/``stop_trace`` for N seconds — or until a
caller-supplied condition (the engine handler uses "M decode steps
dispatched") — and returns the trace directory zipped.

Concurrency: the XLA profiler is a process-global singleton, so exactly
ONE capture may run at a time; a second request while one is in flight
fails fast with :class:`ProfileBusy` (HTTP 409 through the responder's
status_code seam) instead of corrupting the live session.

Parking: where ``jax.profiler`` is unavailable or refuses to start
(stripped containers, backends without a profiler plugin), the capture
*parks* — it still samples the engine/debug state at 10 Hz in pure
Python, archives those samples with the park reason, and reports
``mode="fallback"`` — so the endpoint, its tests, and the CI smoke stay
meaningful on the CPU backend. Even in jax mode the samples ride along
in the archive (``engine_samples.json``): the host-side view of slot
occupancy over the capture window is what makes a device trace
interpretable.
"""

from __future__ import annotations

import io
import json
import math
import os
import tempfile
import threading
import time
import zipfile
from typing import Any, Callable

__all__ = ["ProfileBusy", "ProfilerCapture", "profiler_capture"]

_MAX_SECONDS = 30.0  # past this, use jax's own remote profiling tooling
_SAMPLE_PERIOD_S = 0.1


class ProfileBusy(RuntimeError):
    """A capture is already running. The XLA profiler is process-global —
    carries status_code so the HTTP responder maps it to 409 without a
    handler-side catch (same seam as llm.EngineOverloaded -> 429)."""

    status_code = 409


class ProfilerCapture:
    """One capture at a time; archives the trace dir to zip bytes."""

    def __init__(self, base_dir: str | None = None):
        self._busy = threading.Lock()
        self.base_dir = base_dir

    def _resolve_dir(self, trace_dir: str | None) -> str:
        d = (
            trace_dir
            or self.base_dir
            or os.environ.get("GOFR_PROFILE_DIR")
            or os.path.join(tempfile.gettempdir(), "gofr-tpu-profiles")
        )
        run = os.path.join(d, time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}")
        os.makedirs(run, exist_ok=True)
        return run

    def capture(
        self,
        seconds: float = 2.0,
        *,
        trace_dir: str | None = None,
        sample_fn: Callable[[], Any] | None = None,
        until: Callable[[], bool] | None = None,
    ) -> dict:
        """Run one capture window. `seconds` bounds the window (clamped to
        0.1..30 — an HTTP capture must fit REQUEST_TIMEOUT); `until`
        (e.g. "M decode steps dispatched") ends it early; `sample_fn` is
        polled at 10 Hz and its samples archived alongside the trace.

        Returns {mode, seconds, dir, files, archive, parked?}: `archive`
        is the zip bytes of everything written under `dir`; `mode` is
        "jax" for a real device trace, "fallback" for the parked
        pure-Python capture (with `parked` carrying the reason)."""
        seconds = float(seconds)
        if not math.isfinite(seconds):
            # NaN slips through min/max (all comparisons False) and would
            # make the window infinite with the busy lock held forever
            raise ValueError(f"seconds must be finite, got {seconds}")
        seconds = min(max(seconds, 0.1), _MAX_SECONDS)
        if not self._busy.acquire(blocking=False):
            raise ProfileBusy(
                "a profile capture is already running (the XLA profiler is "
                "process-global; retry when the current capture finishes)"
            )
        try:
            run_dir = self._resolve_dir(trace_dir)
            mode, parked = "jax", None
            try:
                import jax

                jax.profiler.start_trace(run_dir)
            except Exception as e:  # noqa: BLE001 — park, don't fail
                mode, parked = "fallback", f"{type(e).__name__}: {e}"
            samples: list[Any] = []
            t0 = time.perf_counter()
            deadline = t0 + seconds
            try:
                while True:
                    if sample_fn is not None:
                        try:
                            samples.append(sample_fn())
                        except Exception:  # noqa: BLE001 — samples are best-effort
                            pass
                    if until is not None and until():
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    time.sleep(min(_SAMPLE_PERIOD_S, remaining))
            finally:
                # stop_trace runs even when until() (caller code) raises:
                # the XLA profiler is process-global, and leaving it
                # started would park every future capture until restart
                if mode == "jax":
                    try:
                        import jax

                        jax.profiler.stop_trace()
                    except Exception as e:  # noqa: BLE001
                        mode, parked = "fallback", f"stop_trace: {type(e).__name__}: {e}"
            elapsed = time.perf_counter() - t0
            meta = {
                "mode": mode,
                "seconds": round(elapsed, 3),
                "requested_seconds": seconds,
                "samples": len(samples),
            }
            if parked:
                meta["parked"] = parked
            with open(os.path.join(run_dir, "capture.json"), "w", encoding="utf-8") as f:
                json.dump(meta, f, indent=1, default=str)
            if samples:
                with open(
                    os.path.join(run_dir, "engine_samples.json"), "w", encoding="utf-8"
                ) as f:
                    json.dump(samples, f, default=str)
            files, archive = _zip_dir(run_dir)
            return {**meta, "dir": run_dir, "files": files, "archive": archive}
        finally:
            self._busy.release()


def _zip_dir(run_dir: str) -> tuple[list[str], bytes]:
    buf = io.BytesIO()
    names: list[str] = []
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(run_dir):
            for name in sorted(files):
                path = os.path.join(root, name)
                rel = os.path.relpath(path, run_dir)
                names.append(rel)
                z.write(path, rel)
    return names, buf.getvalue()


_capturer = ProfilerCapture()


def profiler_capture() -> ProfilerCapture:
    """The process-wide capturer (the XLA profiler itself is one per
    process, so the guard must be too)."""
    return _capturer
