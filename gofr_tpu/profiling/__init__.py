"""Compilation & device-program observability: the compile observatory.

Everything below the serving engine's request lifecycle was dark before
this subsystem: an XLA compilation triggered by a new shape bucket stalls
live traffic invisibly, and nothing in the process could say which
program compiled, when, for how long, or what it costs to run. GoFr
answers the equivalent question for Go services by exposing pprof next
to its metrics server; this package is the TPU-native analogue — a
**compile registry** fed by ``instrument_jit`` wrappers around every
jitted program the framework owns, plus ``jax.monitoring`` listeners for
the backend's own phase timings.

Three public surfaces:

- :func:`instrument_jit` — wrap a function the way ``jax.jit`` would,
  but with per-signature compile accounting: each distinct abstract
  argument signature is lowered + compiled exactly once through JAX's
  AOT API (so the compile wall time is measured directly, not inferred
  from a first-call envelope), its ``cost_analysis()`` FLOPs/bytes are
  recorded when the backend provides them, and every later call is a
  trace-cache hit counted per program. The registry entry carries the
  program name, abstract arg shapes, compile/trace seconds, and cost.
- :class:`CompileRegistry` / :func:`default_registry` — the process-wide
  store behind ``GET /.well-known/debug/compiles`` and
  ``engine.debug_state()["compiles"]``. Engines remove their entries on
  ``close()`` (a dead engine must not keep listing its programs, the
  same bug class as a dead engine exporting occupancy gauges).
- metrics: ``app_jax_compile_seconds{program,model}`` histograms plus
  compile / trace-cache-hit counters, registered idempotently via
  :func:`register_compile_metrics`.

MFU / roofline math lives in :mod:`gofr_tpu.profiling.mfu`; on-demand
``jax.profiler`` capture in :mod:`gofr_tpu.profiling.capture`.

This module imports no jax at import time — a pure-web app can serve the
(empty) compile registry without initializing a backend.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

__all__ = [
    "CompileRegistry",
    "InstrumentedJit",
    "default_registry",
    "instrument_jit",
    "install_monitoring_listener",
    "register_compile_metrics",
]

# Compile times span four orders of magnitude: a tiny admission scatter
# compiles in ~10 ms on CPU while a sharded Gemma prefill takes tens of
# seconds on a real TPU — the serving TPU_BUCKETS ladder (100us..5s)
# would flatten every interesting compile into +Inf.
COMPILE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# Serializes app_jax_* registration across engines/runtimes (same
# rationale as llm._OBS_REG_LOCK: replicas register on parallel threads).
_REG_LOCK = threading.Lock()


def register_compile_metrics(metrics) -> None:
    """Idempotently register the compile-observatory instruments."""
    with _REG_LOCK:
        if not metrics.has("app_jax_compile_seconds"):
            metrics.new_histogram(
                "app_jax_compile_seconds",
                "XLA compile wall seconds per program signature",
                COMPILE_BUCKETS,
            )
        for name, desc in (
            ("app_jax_compiles_total",
             "XLA compilations per program (new abstract signature)"),
            ("app_jax_trace_cache_hits_total",
             "dispatches served by an already-compiled executable"),
        ):
            if not metrics.has(name):
                metrics.new_counter(name, desc)


class CompileRegistry:
    """Process-wide store of compiled device programs.

    Entries are keyed by (program, model, arg-shape signature) so a
    program that recompiles under shape-bucket churn shows one row per
    bucket. The registry never touches jax: callers hand it plain
    numbers, so it is constructible (and serveable) in a jax-free
    process.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[tuple, dict] = {}
        self._events: dict[str, list] = {}  # jax.monitoring: name -> [n, total_s]
        self._warmups: dict[str, dict] = {}

    # -- writers ----------------------------------------------------------
    def record_compile(
        self,
        *,
        program: str,
        model: str = "",
        arg_shapes: tuple[str, ...] = (),
        trace_s: float = 0.0,
        compile_s: float = 0.0,
        flops: float | None = None,
        bytes_accessed: float | None = None,
        backend: str = "",
        measured: str = "aot",
    ) -> dict:
        key = (program, model, arg_shapes)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = {
                    "program": program,
                    "model": model,
                    "arg_shapes": list(arg_shapes),
                    "compiles": 0,
                    "hits": 0,
                    "trace_s": 0.0,
                    "compile_s": 0.0,
                    "compile_s_total": 0.0,
                    "flops": None,
                    "bytes_accessed": None,
                    "backend": backend,
                    # "aot": lower().compile() timed directly;
                    # "first_call": first-dispatch envelope (compile+execute)
                    "measured": measured,
                    "first_compiled_at": time.time(),
                }
                self._entries[key] = e
            e["compiles"] += 1
            e["trace_s"] = round(trace_s, 6)
            e["compile_s"] = round(compile_s, 6)
            e["compile_s_total"] = round(e["compile_s_total"] + compile_s, 6)
            if flops is not None:
                e["flops"] = flops
            if bytes_accessed is not None:
                e["bytes_accessed"] = bytes_accessed
            return e

    def note_hit(self, program: str, model: str = "", arg_shapes: tuple[str, ...] = ()) -> None:
        key = (program, model, arg_shapes)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e["hits"] += 1

    def note_backend_event(self, event: str, duration_s: float) -> None:
        """Aggregate a jax.monitoring duration event (bounded cardinality:
        jax emits a handful of /jax/core/compile/* phase names)."""
        with self._lock:
            agg = self._events.setdefault(event, [0, 0.0])
            agg[0] += 1
            agg[1] += duration_s

    def record_warmup(self, model: str, seconds: float, programs: int | None = None) -> None:
        """One engine warmup: total compile+execute wall time for the full
        program set (LLMEngine._warm overlaps compiles, so this is wall
        time, not the per-program sum)."""
        with self._lock:
            self._warmups[model] = {
                "seconds": round(seconds, 3),
                "programs": programs,
                "at": time.time(),
            }

    def remove_model(self, model: str) -> int:
        """Engine teardown: drop every entry (and warmup record) the label
        owns so a closed engine stops being listed. Returns entries removed."""
        with self._lock:
            gone = [k for k in self._entries if k[1] == model]
            for k in gone:
                del self._entries[k]
            self._warmups.pop(model, None)
            return len(gone)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._events.clear()
            self._warmups.clear()

    # -- readers ----------------------------------------------------------
    def snapshot(self, model: str | None = None) -> dict:
        """JSON-able view for /.well-known/debug/compiles. Bounded by the
        process's executable set (the engine's whole point is a bounded
        program count), so safe to serve under load."""
        now = time.time()
        with self._lock:
            entries = [
                dict(e, age_s=round(now - e["first_compiled_at"], 1))
                for k, e in self._entries.items()
                if model is None or k[1] == model
            ]
            events = {k: {"count": v[0], "total_s": round(v[1], 4)} for k, v in self._events.items()}
            warmups = {
                m: dict(w) for m, w in self._warmups.items()
                if model is None or m == model
            }
        entries.sort(key=lambda e: (e["model"], e["program"], e["arg_shapes"]))
        for e in entries:
            e.pop("first_compiled_at", None)
        return {
            "programs": entries,
            "totals": {
                "programs": len(entries),
                "compiles": sum(e["compiles"] for e in entries),
                "cache_hits": sum(e["hits"] for e in entries),
                "compile_s_total": round(sum(e["compile_s_total"] for e in entries), 3),
            },
            "backend_events": events,
            "warmup": warmups,
        }


_default_registry = CompileRegistry()


def default_registry() -> CompileRegistry:
    """The process-wide registry every framework jit wrapper records into
    (one process = one XLA client = one program population; mirrors the
    process-wide persistent compilation cache)."""
    return _default_registry


# -- jax.monitoring bridge -------------------------------------------------

_monitoring_installed = False


def install_monitoring_listener() -> bool:
    """Register a jax.monitoring duration listener that aggregates the
    backend's own compile-phase timings (jaxpr trace, MLIR lowering,
    backend compile) into the DEFAULT registry — the events carry no
    program identity, so they always belong to the process-global view,
    never a wrapper-local registry. Idempotent; returns False where the
    API is unavailable. The listener survives engine teardown
    deliberately: it carries no per-engine labels to leak."""
    global _monitoring_installed
    with _REG_LOCK:  # replicas build engines on parallel threads
        if _monitoring_installed:
            return True
        try:
            import jax.monitoring as jm

            def _on_duration(event: str, duration: float, **_kw) -> None:
                if "compile" in event or "trace" in event:
                    default_registry().note_backend_event(event, duration)

            jm.register_event_duration_secs_listener(_on_duration)
        except Exception:  # noqa: BLE001 — monitoring is additive only
            return False
        _monitoring_installed = True
        return True


# -- the jit wrapper -------------------------------------------------------


def _describe_args(args: tuple) -> tuple[str, ...]:
    """Human-readable per-argument shapes for registry rows: arrays as
    dtype[shape], pytrees collapsed to their leaf count (a 2B-param tree
    listed leaf-by-leaf would drown the row)."""
    out: list[str] = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            out.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(a, (dict, list, tuple)) or hasattr(a, "_fields"):
            import jax

            out.append(f"pytree[{len(jax.tree.leaves(a))} leaves]")
        else:
            out.append(type(a).__name__)
    return tuple(out)


class InstrumentedJit:
    """``jax.jit`` with compile accounting and an explicit executable cache.

    Dispatch path: the abstract signature of the arguments (leaf shapes,
    dtypes, weak types + treedef) keys a dict of AOT-compiled
    executables. A hit calls the executable directly (same cost class as
    jit's own cache lookup); a miss runs ``lower()`` / ``compile()``
    with the two phases timed separately, records the entry (with
    ``cost_analysis()`` FLOPs/bytes where the backend provides them),
    and installs the executable. Donation and input shardings flow
    through lowering unchanged, so engine semantics are identical.

    If an AOT call ever rejects its inputs (a committed-device or layout
    drift the signature missed), the wrapper logs the entry as degraded
    and permanently falls back to plain jit dispatch, where compiles are
    still counted per signature but timed as first-call envelopes.
    """

    def __init__(
        self,
        program: str,
        fn: Callable,
        *,
        model: str = "",
        registry: CompileRegistry | None = None,
        metrics=None,
        donate_argnums: tuple[int, ...] = (),
        static_argnums: tuple[int, ...] = (),
        **jit_kw,
    ):
        import jax

        self.program = program
        self.model = model
        self.registry = registry if registry is not None else default_registry()
        self.metrics = metrics
        self._static = tuple(static_argnums)
        self._jitted = jax.jit(
            fn, donate_argnums=donate_argnums,
            static_argnums=static_argnums or None, **jit_kw,
        )
        self._lock = threading.Lock()
        self._compiled: dict[Any, Any] = {}
        self._shapes: dict[Any, tuple[str, ...]] = {}
        self._seen: set = set()
        self._aot = True
        self._arg0_memo: tuple | None = None
        self._memo_miss_streak = 0
        install_monitoring_listener()

    # jax.jit API passthroughs used by callers/tests
    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)

    def _dyn_args(self, args: tuple) -> tuple:
        """AOT Compiled.__call__ takes only the traced arguments — static
        values were baked in at lowering and must be dropped."""
        if not self._static:
            return args
        return tuple(a for i, a in enumerate(args) if i not in self._static)

    def _leaf_sigs(self, tree) -> tuple:
        import jax

        sig = []
        for x in jax.tree.leaves(tree):
            shape = getattr(x, "shape", None)
            if shape is not None:
                sig.append((
                    tuple(shape), str(getattr(x, "dtype", "")),
                    bool(getattr(x, "weak_type", False)),
                ))
            elif isinstance(x, (bool, int, float, complex)):
                # jit traces Python scalars as weak-typed values: ONE
                # executable per dtype, never one per value — keying by
                # repr would recompile on every distinct scalar
                sig.append(("py", type(x).__name__))
            else:
                sig.append(("pyval", repr(x)))
        return tuple(sig)

    def _signature(self, args: tuple):
        import jax

        # Identity memo for the leading argument: every framework op takes
        # the (immutable, engine-retained) params pytree first, and its
        # per-call structure+leaf walk is the only part of the signature
        # whose cost scales with model size. Same object -> same tree and
        # shapes; the memo holds a strong ref so the identity can never
        # be recycled. The varying tail (tokens, caches, rng) stays small.
        # static args are jit-compile-time CONSTANTS: key them by value,
        # or two calls differing only in a static argument would collide
        # on one executable and misread the mismatch as layout drift
        static = tuple(
            (i, repr(args[i])) for i in self._static if i < len(args)
        )
        if args and isinstance(args[0], (dict, list, tuple)):
            memo = self._arg0_memo
            if memo is not None and memo[0] is args[0]:
                head = memo[1]
                self._memo_miss_streak = 0
            else:
                head = (jax.tree.structure(args[0]), self._leaf_sigs(args[0]))
                # The memo holds a strong ref to arg0. Callers that REBIND
                # it every call (train steps: params = apply_updates(...))
                # would have the memo pin a whole dead parameter tree in
                # device memory between steps — after two consecutive
                # identity misses, stop memoizing for this wrapper.
                self._memo_miss_streak += 1
                self._arg0_memo = (
                    (args[0], head) if self._memo_miss_streak < 2 else None
                )
            tail = args[1:]
            return (static, head, jax.tree.structure(tail), self._leaf_sigs(tail))
        return (static, None, jax.tree.structure(args), self._leaf_sigs(args))

    def __call__(self, *args):
        sig = self._signature(args)
        exe = self._compiled.get(sig)
        if exe is not None:
            self.registry.note_hit(self.program, self.model, self._shapes[sig])
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_jax_trace_cache_hits_total",
                    program=self.program, model=self.model,
                )
            try:
                return exe(*self._dyn_args(args))
            except Exception:
                # Committed-device/layout drift the signature missed: fall
                # back to jit dispatch for good rather than failing serving.
                # But ONLY when the inputs are intact — a failure after the
                # executable consumed a donated buffer (engine chunk/insert
                # ops donate their caches) must propagate, or the retry
                # dies on 'array deleted' and masks the real error.
                import jax

                if any(
                    getattr(x, "is_deleted", lambda: False)()
                    for x in jax.tree.leaves(args)
                ):
                    raise
                with self._lock:
                    self._aot = False
                    self._compiled.clear()  # _seen still routes hits to jit
                return self._jitted(*args)
        if sig in self._seen:  # degraded mode hit
            self.registry.note_hit(self.program, self.model, self._shapes[sig])
            if self.metrics is not None:
                self.metrics.increment_counter(
                    "app_jax_trace_cache_hits_total",
                    program=self.program, model=self.model,
                )
            return self._jitted(*args)
        return self._compile_and_call(sig, args)

    def _compile_and_call(self, sig, args: tuple):
        """Miss path. Not serialized across signatures on purpose: the
        engine's warmup pool compiles different widths concurrently and
        XLA releases the GIL while compiling."""
        shapes = _describe_args(args)
        with self._lock:
            self._shapes.setdefault(sig, shapes)
        compiled = None
        if self._aot:
            # tracing errors propagate — plain jit would raise identically,
            # and a bad input batch must not degrade the wrapper for good
            t0 = time.perf_counter()
            lowered = self._jitted.lower(*args)
            t1 = time.perf_counter()
            try:
                compiled = lowered.compile()
            except Exception:  # noqa: BLE001 — AOT unsupported here; degrade
                with self._lock:
                    self._aot = False
        if compiled is not None:
            # install + record BEFORE the first execution: a runtime
            # failure there must neither hide the (expensive) compile from
            # the registry nor discard the executable — the retry then
            # takes the hit path instead of re-paying lower()+compile()
            with self._lock:
                self._compiled[sig] = compiled
                self._seen.add(sig)
            self._record(shapes, {
                "trace_s": t1 - t0,
                "compile_s": time.perf_counter() - t1,
                "measured": "aot",
                **_cost_of(compiled),
            })
            return compiled(*self._dyn_args(args))
        t0 = time.perf_counter()
        out = self._jitted(*args)
        with self._lock:
            self._seen.add(sig)
        self._record(shapes, {
            "compile_s": time.perf_counter() - t0,
            "measured": "first_call",
        })
        return out

    def _record(self, shapes: tuple[str, ...], entry_kw: dict) -> None:
        import jax

        self.registry.record_compile(
            program=self.program, model=self.model, arg_shapes=shapes,
            backend=jax.default_backend(), **entry_kw,
        )
        if self.metrics is not None:
            register_compile_metrics(self.metrics)
            self.metrics.record_histogram(
                "app_jax_compile_seconds",
                entry_kw.get("compile_s", 0.0) + entry_kw.get("trace_s", 0.0),
                program=self.program, model=self.model,
            )
            self.metrics.increment_counter(
                "app_jax_compiles_total", program=self.program, model=self.model,
            )


def _cost_of(compiled) -> dict:
    """FLOPs / bytes-accessed from Compiled.cost_analysis() where the
    backend provides it (list-of-dicts on CPU/TPU; None/raises on some
    backends — the registry entry simply omits the numbers then)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — optional per backend
        return {}
    if ca is None:
        return {}
    if isinstance(ca, dict):
        ca = [ca]
    try:
        flops = sum(float(c.get("flops", 0.0)) for c in ca)
        bytes_accessed = sum(float(c.get("bytes accessed", 0.0)) for c in ca)
    except Exception:  # noqa: BLE001
        return {}
    out: dict[str, float] = {}
    if flops:
        out["flops"] = flops
    if bytes_accessed:
        out["bytes_accessed"] = bytes_accessed
    return out


def instrument_jit(
    program: str,
    fn: Callable,
    *,
    model: str = "",
    registry: CompileRegistry | None = None,
    metrics=None,
    donate_argnums: tuple[int, ...] = (),
    static_argnums: tuple[int, ...] = (),
    **jit_kw,
) -> InstrumentedJit:
    """Drop-in ``jax.jit`` replacement for framework-owned programs: same
    call surface, plus compile registry + app_jax_* metrics accounting.
    See :class:`InstrumentedJit`."""
    return InstrumentedJit(
        program, fn, model=model, registry=registry, metrics=metrics,
        donate_argnums=donate_argnums, static_argnums=static_argnums, **jit_kw,
    )
