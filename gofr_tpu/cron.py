"""In-process cron scheduler.

Parity: reference pkg/gofr/cron.go — 5-field crontab parser with ``*``,
lists, ranges and ``/n`` steps (cron.go:86-216), a minutely ticker that
snapshots due jobs and runs each concurrently wrapped in a span + duration
log (cron.go:61-75,218-254). Re-design: jobs run as asyncio tasks on the
app loop (sync jobs hop to the executor) instead of goroutines.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable

FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))  # min hour dom mon dow


class CronScheduleError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            try:
                step = int(step_s)
            except ValueError as e:
                raise CronScheduleError(f"bad step {step_s!r}") from e
            if step <= 0:
                raise CronScheduleError(f"bad step {step}")
        if part in ("*", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            try:
                lo2, hi2 = int(a), int(b)
            except ValueError as e:
                raise CronScheduleError(f"bad range {part!r}") from e
        else:
            try:
                lo2 = hi2 = int(part)
            except ValueError as e:
                raise CronScheduleError(f"bad value {part!r}") from e
        if lo2 < lo or hi2 > hi or lo2 > hi2:
            raise CronScheduleError(f"value out of range [{lo},{hi}]: {part!r}")
        out.update(range(lo2, hi2 + 1, step))
    return frozenset(out)


class Schedule:
    """Parsed 5-field crontab expression."""

    __slots__ = ("minutes", "hours", "days", "months", "weekdays", "expr")

    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise CronScheduleError(f"schedule must have 5 fields, got {len(fields)}: {expr!r}")
        self.expr = expr
        sets = [_parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, FIELD_RANGES)]
        self.minutes, self.hours, self.days, self.months, self.weekdays = sets

    def matches(self, t: time.struct_time) -> bool:
        # struct_time.tm_wday: Monday=0; cron: Sunday=0
        dow = (t.tm_wday + 1) % 7
        return (
            t.tm_min in self.minutes
            and t.tm_hour in self.hours
            and t.tm_mday in self.days
            and t.tm_mon in self.months
            and dow in self.weekdays
        )


class Job:
    __slots__ = ("schedule", "name", "fn")

    def __init__(self, schedule: Schedule, name: str, fn: Callable):
        self.schedule = schedule
        self.name = name
        self.fn = fn


class Cron:
    """Minutely ticker dispatching due jobs (cron.go:61-75)."""

    def __init__(self, container):
        self.container = container
        self.jobs: list[Job] = []

    def add_job(self, schedule: str, job_name: str, fn: Callable) -> None:
        self.jobs.append(Job(Schedule(schedule), job_name, fn))

    async def _run_job(self, job: Job) -> None:
        tracer = getattr(self.container, "tracer", None)
        span = tracer.start_span(f"cron:{job.name}") if tracer else None
        start = time.perf_counter()
        try:
            if asyncio.iscoroutinefunction(job.fn):
                await job.fn(self._job_context())
            else:
                await asyncio.get_running_loop().run_in_executor(None, job.fn, self._job_context())
            self.container.logger.debug(
                f"cron job {job.name} completed in {int((time.perf_counter() - start) * 1e6)}us"
            )
        except Exception as e:  # noqa: BLE001 - a failing job must not kill the ticker
            self.container.logger.error(f"cron job {job.name} failed: {e!r}")
        finally:
            if span:
                span.end()

    def _job_context(self):
        from .context import Context

        return Context(_CronRequest(), self.container)

    def run_due(self, now: float | None = None) -> list[asyncio.Task]:
        t = time.localtime(now if now is not None else time.time())
        return [asyncio.ensure_future(self._run_job(j)) for j in self.jobs if j.schedule.matches(t)]

    async def run(self) -> None:
        # Align to minute boundaries like the reference's time.Ticker(minute)
        while True:
            now = time.time()
            await asyncio.sleep(60 - (now % 60) + 0.01)
            self.run_due()


class _CronRequest:
    """Empty request so cron jobs get a normal Context."""

    def __init__(self):
        self.context: dict = {}

    def param(self, _key: str) -> str:
        return ""

    def params(self, _key: str) -> list[str]:
        return []

    def path_param(self, _key: str) -> str:
        return ""

    def bind(self, _target=None):
        return None

    def header(self, _key: str) -> str:
        return ""

    def host_name(self) -> str:
        return ""
