"""Framework version, stamped into logs and metrics.

Parity: reference pkg/gofr/version/version.go:3.
"""

FRAMEWORK = "0.4.0"
