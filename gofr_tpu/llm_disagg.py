"""Disaggregated prefill/decode serving (docs/advanced-guide/sharded-serving.md).

One colocated engine interleaves prefill chunks and decode chunks on the
same chips, so a burst of long prompts steals decode steps from every
interactive stream (BENCH_r05's target_note: "single-chip infeasible at
128-tok prompts"). :class:`DisaggregatedLLMEngine` splits a replicated
fleet into two role pools instead — the DistServe/Splitwise serving
shape:

- **prefill pool** — replicas that run chunked prefill only: every
  request enters as an internal ``max_new_tokens=1`` probe whose prompt
  KV the engine publishes into its radix tree (gofr_tpu.kvcache.paged)
  with the last-token logits at prefill completion.
- **KV handoff** — the published blocks are gathered
  (``LLMEngine.kv_handoff_export``) and moved to a decode replica:
  direct ``jax.device_put`` onto the decode engine's committed
  device/submesh placement when possible, byte-identical host staging
  as the fallback and the A/B test oracle
  (``TPU_LLM_KV_HANDOFF_D2D=0``). The decode engine adopts them
  (``kv_handoff_import``) as an exact radix record WITH logits.
- **decode pool** — the caller's real request then admits on a decode
  replica as an exact prefix hit: prefill is skipped entirely, the
  first token re-samples from the transferred logits, and decode runs
  against the transferred blocks. Greedy outputs are token-identical to
  the colocated engine by construction — the exact-hit path is already
  pinned token-equal to the uncached path, and the handoff moves bytes.

Routing is by ROLE-SPECIFIC load: prefill replicas by queued prompt
tokens (their ``load_tokens`` is prompt-dominated — the internal probes
decode exactly one token), decode replicas by resident slots. Every
failure path degrades to a colocated submit — a dead decode pool
re-prefills on a live prefill replica, a dropped/evicted publish or an
exhausted pool simply costs a re-prefill on the decode side — so
disaggregation is an optimization with a correctness floor, never a new
failure mode. ``TPU_LLM_DISAGG=0`` (or just not building this class)
restores the colocated engine exactly.

Observability: ``app_llm_kv_handoff_seconds`` (submit -> decode-admit
handoff wall), ``app_llm_kv_handoffs_total{outcome=ok|miss|fallback}``,
``app_llm_collective_seconds{phase=kv_handoff_*}``, and per-role
``role="prefill"|"decode"`` labels on the engine phase histograms
(TTFT/TPOT/step walls split per pool).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

__all__ = ["DisaggregatedLLMEngine"]


class DisaggregatedLLMEngine:
    """Prefill-role and decode-role replica pools behind one
    LLMEngine-shaped surface (submit/generate/stats/drain/close).

    Construction mirrors :class:`~gofr_tpu.llm.ReplicatedLLMEngine` —
    ``replicas``/``devices`` for single-chip replicas, ``meshes`` for
    tensor-parallel submesh replicas — plus ``prefill_replicas``: the
    first P placements become the prefill pool, the rest decode. Each
    pool is a full ReplicatedLLMEngine (supervision, elastic rebuild,
    canary gates, in-pool failover), sharing ONE fairness ledger so
    per-client weighted ordering holds across roles.
    """

    def __init__(
        self,
        cfg,
        params,
        *,
        prefill_replicas: int | None = None,
        replicas: int | None = None,
        devices: list | None = None,
        meshes: list | None = None,
        handoff_timeout_s: float | None = None,
        handoff_d2d: bool | None = None,
        handoff_workers: int = 32,
        logger=None,
        supervise: bool = True,
        version: str = "v1",
        **engine_kw,
    ):
        import jax

        from .llm import EngineStoppedError  # noqa: F401 (re-raise type)
        from .llm import ReplicatedLLMEngine
        from .metrics import RollingWindow

        if engine_kw.get("kv_paged") is False:
            raise ValueError(
                "disaggregated serving requires the paged KV pool "
                "(kv_paged=False / TPU_LLM_KV_PAGED=0 cannot hand off "
                "blocks)"
            )
        if "mesh" in engine_kw or "param_specs" in engine_kw:
            # a single whole-slice mesh forwarded to every replica would
            # put both role pools on the SAME chips (the pool split a
            # no-op, the "handoff" a self-transfer, weights resident
            # once per replica) — TP disaggregation takes meshes=[...],
            # one disjoint submesh per replica (parallel.tp_submeshes)
            raise ValueError(
                "disaggregated serving takes meshes=[(mesh, specs), ...] "
                "(one disjoint submesh per replica), not a single "
                "mesh/param_specs pair shared by every replica"
            )
        # the handoff rides the radix tree: force a retention budget when
        # neither the prefix cache nor the session tier asked for one
        if (
            float(engine_kw.get("prefix_cache_mb") or 0.0) <= 0
            and float(engine_kw.get("session_mb") or 0.0) <= 0
        ):
            engine_kw["prefix_cache_mb"] = 64.0
        if prefill_replicas is None:
            prefill_replicas = int(
                os.environ.get("TPU_LLM_DISAGG_PREFILL_REPLICAS", "1") or 1
            )
        if handoff_timeout_s is None:
            handoff_timeout_s = float(
                os.environ.get("TPU_LLM_KV_HANDOFF_TIMEOUT_S", "10") or 10.0
            )
        if handoff_d2d is None:
            handoff_d2d = os.environ.get("TPU_LLM_KV_HANDOFF_D2D", "1") != "0"
        self.handoff_timeout_s = max(0.1, float(handoff_timeout_s))
        self.handoff_d2d = bool(handoff_d2d)
        self.logger = logger
        self.metrics = engine_kw.get("metrics")
        # trace continuity across the disagg seam: the probe, the KV
        # handoff, and the decode admit are phases of ONE caller journey —
        # submit() captures the caller's context (the handoff executor
        # threads never see the contextvar) and every phase span parents
        # under it (docs/advanced-guide/observability-serving.md#journeys)
        self.tracer = engine_kw.get("tracer")
        self.label = engine_kw.pop("kv_label", "llm")
        self.version = str(version)

        # -- split the placements into the two role pools -----------------
        pre_spec: dict[str, Any] = {}
        dec_spec: dict[str, Any] = {}
        if meshes is not None:
            P = int(prefill_replicas)
            if not (0 < P < len(meshes)):
                raise ValueError(
                    f"prefill_replicas={P} must leave both pools non-empty "
                    f"over {len(meshes)} meshes"
                )
            pre_spec["meshes"] = meshes[:P]
            dec_spec["meshes"] = meshes[P:]
        else:
            if devices is None:
                devs = jax.devices()
                n = max(2, int(replicas or 2))
                # round-robin when the host has fewer chips than replica
                # slots (the 1-device CPU case): the two pools then share
                # chips — correctness-identical, the role split still
                # isolates scheduling
                devices = [devs[i % len(devs)] for i in range(n)]
            P = int(prefill_replicas)
            if not (0 < P < len(devices)):
                raise ValueError(
                    f"prefill_replicas={P} must leave both pools non-empty "
                    f"over {len(devices)} devices"
                )
            pre_spec["devices"] = devices[:P]
            dec_spec["devices"] = devices[P:]
        self.prefill_replicas = P

        # ONE fairness ledger across both pools: least-served ordering
        # must hold no matter which role a request's work lands on
        from .resilience import FairLedger

        fq = engine_kw.get("fair_queuing")
        if fq is None:
            fq = os.environ.get("TPU_LLM_FAIR", "1") != "0"
        if fq and engine_kw.get("fair_ledger") is None:
            engine_kw["fair_ledger"] = FairLedger(
                engine_kw.pop("fair_weights", None)
            )

        self._stop = False
        self._draining = False
        self.submitted = 0
        self.handoffs_ok = 0  # decode admitted on transferred blocks
        self.handoffs_miss = 0  # handoff unavailable -> decode re-prefilled
        self.fallbacks = 0  # whole requests served colocated (pool down)
        self._handoff_window = RollingWindow()
        n_dec = (len(meshes) - P) if meshes is not None else (len(devices) - P)
        if logger is not None:
            logger.info(
                f"disaggregated LLM serving: {P} prefill + {n_dec} decode "
                f"replicas, handoff "
                f"{'d2d' if self.handoff_d2d else 'host-staged'}, "
                f"timeout {self.handoff_timeout_s:.1f}s"
            )
        self.prefill = ReplicatedLLMEngine(
            cfg, params, logger=logger, supervise=supervise,
            version=version, kv_label=f"{self.label}/prefill",
            role="prefill", **pre_spec, **engine_kw,
        )
        try:
            self.decode = ReplicatedLLMEngine(
                cfg, params, logger=logger, supervise=supervise,
                version=version, kv_label=f"{self.label}/decode",
                role="decode", **dec_spec, **engine_kw,
            )
        except BaseException:
            self.prefill.close()
            raise
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(handoff_workers)),
            thread_name_prefix="llm-disagg-handoff",
        )
        self._lock = threading.Lock()

    # -- role-specific routing --------------------------------------------
    def _pick_prefill(self, exclude: set | frozenset = frozenset()):
        """Least queued PROMPT tokens. The prefill pool only ever runs
        the internal max_new=1 probes, so each engine's load_tokens IS
        its queued prompt tokens (plus one decode step per probe)."""
        live = [
            e for e in self.prefill.engines
            if e.accepting() and id(e) not in exclude
        ]
        if not live:
            return None
        return min(live, key=lambda e: (e.load_tokens(), e.load()))

    def _pick_decode(self, exclude: set | frozenset = frozenset()):
        """Fewest RESIDENT decode slots (streams being served right
        now); queue depth and queued tokens break ties."""
        live = [
            e for e in self.decode.engines
            if e.accepting() and id(e) not in exclude
        ]
        if not live:
            return None
        return min(
            live,
            key=lambda e: (e.resident_slots(), e.load(), e.load_tokens()),
        )

    # -- LLMEngine surface --------------------------------------------------
    def submit(self, req):
        from .llm import (
            EngineDraining,
            EngineStoppedError,
            GenRequest,
        )

        if self._stop:
            raise EngineStoppedError("engine stopped")
        if self._draining:
            raise EngineDraining("engine draining (rolling deploy)")
        with self._lock:
            self.submitted += 1
        # capture the caller's trace context HERE, on the submitting
        # thread — _serve runs on the handoff executor where the tracing
        # contextvar is empty, and without this stamp the probe and the
        # decode-side request would each start a FRESH trace (the
        # shattered-journey bug this threading exists to fix)
        if self.tracer is not None and req.traceparent is None:
            from .tracing import current_span

            cs = current_span()
            if cs is not None and cs.end_ns == 0:
                req.traceparent = cs.traceparent
        if req.session_id:
            # conversation KV lives with the decode pool (the publishing
            # side); routing turns through the prefill pool would
            # re-prefill the whole history every time. The decode fleet's
            # session affinity serves these colocated.
            return self.decode.submit(req)
        peng = self._pick_prefill()
        if peng is None:
            # prefill pool down: degrade to colocated on the decode pool
            # (it re-prefills) — capacity shrinks, requests never bounce
            with self._lock:
                self.fallbacks += 1
            self._count_handoff("fallback")
            return self.decode.submit(req)
        dspan = None
        if self.tracer is not None:
            from .tracing import parse_traceparent

            # one detached journey span for the whole disagg decision:
            # the prefill probe's llm.request, the handoff phases, and
            # the decode-side llm.request all parent under it, so the
            # stitcher renders probe -> handoff -> decode as ONE subtree
            dspan = self.tracer.start_detached_span(
                "llm.disagg",
                parent=parse_traceparent(req.traceparent),
                attributes={
                    "llm.model": self.label,
                    "llm.request_id": req.id,
                    "llm.prompt_tokens": len(req.prompt_tokens),
                },
            )
            req.traceparent = dspan.traceparent
            if req.journey_id is None:
                req.journey_id = dspan.trace_id
        preq = GenRequest(
            list(req.prompt_tokens), max_new_tokens=1, temperature=0.0,
            eos_token=-1, priority=req.priority, client=req.client,
            deadline=req.deadline, traceparent=req.traceparent,
        )
        # synchronous prefill-pool admission: overload/validation errors
        # (429 + Retry-After, prompt-too-long) surface to the CALLER,
        # exactly like a colocated submit — backpressure must not vanish
        # into the handoff executor
        tried: set[int] = set()
        while True:
            try:
                peng.submit(preq)
                break
            except (EngineStoppedError, EngineDraining):
                tried.add(id(peng))
                peng = self._pick_prefill(exclude=tried)
                if peng is None:
                    # raced the whole pool away: colocated fallback
                    with self._lock:
                        self.fallbacks += 1
                    self._count_handoff("fallback")
                    if dspan is not None:
                        dspan.set_attribute("llm.disagg.outcome", "fallback")
                        dspan.end()
                    return self.decode.submit(req)
        t0 = time.perf_counter()
        self._pool.submit(self._serve, req, peng, preq, t0, dspan)
        return req

    def _rec_phase(self, dspan, name: str, t0_ns: int, attrs: dict) -> None:
        """Retrospective child span for one handoff phase (worker thread,
        wall-clock anchored — same pattern as LLMEngine._phase_span)."""
        if dspan is None or self.tracer is None:
            return
        self.tracer.record_span(
            name, trace_id=dspan.trace_id, parent_id=dspan.span_id,
            start_ns=t0_ns, end_ns=time.time_ns(), attributes=attrs,
        )

    def _serve(self, req, peng, preq, t0: float, dspan=None) -> None:
        """Handoff worker: wait out the prefill probe, move the prompt's
        KV blocks to a decode replica, then hand the caller's request to
        it (an exact radix hit — prefill skipped). Every failure mode
        falls back to a colocated submit; the stream only errors when NO
        live replica exists anywhere."""
        try:
            probe_t0 = time.time_ns()
            try:
                preq.tokens(timeout=max(60.0, self.handoff_timeout_s))
                prefilled = preq.finish_reason in ("eos", "length")
            except Exception:  # noqa: BLE001 — probe died with its replica
                prefilled = False
            self._rec_phase(dspan, "disagg.prefill_probe", probe_t0, {
                "llm.request_id": req.id,
                "disagg.prefilled": prefilled,
            })
            handoff_t0 = time.time_ns()
            payload = None
            if prefilled and peng.alive():
                try:
                    payload = peng.kv_handoff_export(
                        req.prompt_tokens, timeout=self.handoff_timeout_s
                    )
                except Exception as e:  # noqa: BLE001 — export is best-effort
                    if self.logger is not None:
                        self.logger.warn(f"kv handoff export failed: {e!r}")
                    payload = None
            handoff_bytes = sum(
                int(getattr(payload.get(k), "nbytes", 0) or 0)
                for k in ("k", "v")
            ) if payload is not None else 0
            deng = self._pick_decode()
            imported = False
            if deng is not None and payload is not None:
                try:
                    payload = self._transfer(payload, deng)
                    imported = deng.kv_handoff_import(
                        payload, timeout=self.handoff_timeout_s
                    )
                except Exception as e:  # noqa: BLE001 — import is best-effort
                    if self.logger is not None:
                        self.logger.warn(f"kv handoff import failed: {e!r}")
                    imported = False
            admit_t0 = time.time_ns()
            placed_on = self._submit_decode(req, deng)
            # outcome AFTER placement: "ok" means the request was
            # actually accepted by the replica holding the transferred
            # blocks — an import whose target died/drained before the
            # submit re-prefilled elsewhere and is a miss, not a win
            if imported and placed_on is deng:
                outcome = "ok"
                dt = time.perf_counter() - t0
                with self._lock:
                    self.handoffs_ok += 1
                self._handoff_window.observe(dt)
                self._count_handoff("ok")
                if self.metrics is not None:
                    self.metrics.record_histogram(
                        "app_llm_kv_handoff_seconds", dt, model=self.label,
                        exemplar=(
                            {"trace_id": dspan.trace_id}
                            if dspan is not None else None
                        ),
                    )
            else:
                outcome = "miss"
                with self._lock:
                    self.handoffs_miss += 1
                self._count_handoff("miss")
            self._rec_phase(dspan, "disagg.kv_handoff", handoff_t0, {
                "llm.request_id": req.id,
                "disagg.outcome": outcome,
                "disagg.bytes": handoff_bytes,
                "disagg.imported": imported,
            })
            self._rec_phase(dspan, "disagg.decode_admit", admit_t0, {
                "llm.request_id": req.id,
                "disagg.placed": placed_on is not None,
                "disagg.on_transfer_target": placed_on is deng,
            })
            if dspan is not None:
                dspan.set_attribute("llm.disagg.outcome", outcome)
                dspan.set_attribute("llm.disagg.bytes", handoff_bytes)
                if placed_on is None:
                    dspan.set_status("ERROR")
                dspan.end()
        except BaseException as e:  # noqa: BLE001 — the stream must terminate
            if self.logger is not None:
                self.logger.error(f"disaggregated serve failed: {e!r}")
            if dspan is not None and dspan.end_ns == 0:
                dspan.set_attribute("error", repr(e))
                dspan.set_status("ERROR")
                dspan.end()
            if req.finish_reason is None:
                req.finish_reason = "error"
                req.out.put(None)

    def _submit_decode(self, req, deng):
        """Place the caller's request: the import target first, then the
        rest of the decode pool, then the prefill pool (colocated
        re-prefill — the handoff-failure failover the tests pin).
        Overloaded replicas are waited out inside a bounded window.
        Returns the engine the request landed on (None = stream
        errored: no live replica anywhere / deadline spent)."""
        from .llm import EngineDraining, EngineOverloaded, EngineStoppedError

        deadline = time.perf_counter() + max(5.0, self.handoff_timeout_s)
        tried: set[int] = set()
        fell_back = False
        while True:
            eng = deng if (deng is not None and id(deng) not in tried) else None
            if eng is None:
                eng = self._pick_decode(exclude=tried)
            if eng is None:
                # decode pool gone: re-prefill colocated on the prefill
                # pool — token-identical, counted as a fallback
                eng = self._pick_prefill(exclude=tried)
                if eng is None:
                    if req.finish_reason is None:
                        req.finish_reason = "error"
                        req.out.put(None)
                    return None
                if not fell_back:
                    fell_back = True
                    with self._lock:
                        self.fallbacks += 1
                    self._count_handoff("fallback")
            try:
                eng.submit(req)
                return eng
            except (EngineStoppedError, EngineDraining):
                tried.add(id(eng))
            except EngineOverloaded:
                if time.perf_counter() >= deadline:
                    if req.finish_reason is None:
                        req.finish_reason = "error"
                        req.out.put(None)
                    return None
                time.sleep(0.05)

    def _transfer(self, payload: dict, deng) -> dict:
        """Move an export payload onto the decode engine's placement:
        direct device-to-device ``jax.device_put`` against the
        committed device/submesh when enabled and available, else
        byte-identical host staging (numpy) — the CPU/old-jax fallback
        and the equality tests' oracle."""
        import jax
        import numpy as np

        t0 = time.perf_counter()
        target = deng.kv_placement() if self.handoff_d2d else None
        # a NamedSharding target describes the 5-D pool layout: only the
        # K/V stacks match its rank — scales/logits host-stage alongside
        pool_only = target is not None and hasattr(target, "spec")

        def move(a, pool_shaped: bool):
            if a is None:
                return None
            if target is None or (pool_only and not pool_shaped):
                return np.asarray(a)
            return jax.device_put(a, target)

        out = dict(
            payload,
            k=move(payload["k"], True),
            v=move(payload["v"], True),
            sc=move(payload.get("sc"), False),
            logits=move(payload.get("logits"), False),
        )
        for key in ("k", "v"):
            if hasattr(out[key], "block_until_ready"):
                out[key].block_until_ready()
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_llm_collective_seconds", time.perf_counter() - t0,
                model=self.label, phase="kv_handoff_transfer",
            )
        return out

    def _count_handoff(self, outcome: str) -> None:
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_llm_kv_handoffs_total", model=self.label,
                outcome=outcome,
            )

    def generate(self, prompt_tokens: list[int], **kw) -> list[int]:
        from .llm import GenRequest

        return self.submit(GenRequest(prompt_tokens, **kw)).tokens()

    def deploy(self, *a, **kw):
        """Weight rollouts are not yet wired for disaggregated fleets —
        raise loudly. Without this, ModelHandle.deploy's hasattr
        dispatch would fall through to the bare-engine swap rollout and
        silently replace the whole prefill/decode topology with one
        default single-chip engine. Roll the pools by process
        replacement behind the drain lifecycle instead
        (docs/advanced-guide/sharded-serving.md)."""
        from .resilience.rollout import RolloutError

        raise RolloutError(
            "weight rollouts are not supported for disaggregated "
            "prefill/decode fleets yet; drain and replace the process "
            "instead"
        )

    # -- aggregate views ----------------------------------------------------
    @property
    def engines(self):
        return list(self.prefill.engines) + list(self.decode.engines)

    def load(self) -> int:
        return self.prefill.load() + self.decode.load()

    def load_tokens(self) -> int:
        return self.prefill.load_tokens() + self.decode.load_tokens()

    def throughput_tok_s(self) -> float | None:
        """Pooled measured throughput across BOTH role pools — the
        scale-out fleet view reads one number per process
        (docs/advanced-guide/scale-out.md)."""
        vals = [
            p.throughput_tok_s() for p in (self.prefill, self.decode)
        ]
        tput = sum(v for v in vals if v)
        return tput if tput > 1e-9 else None

    def predicted_wait_s(self) -> float | None:
        tput = self.throughput_tok_s()
        if tput is None:
            return None
        return self.load_tokens() / tput

    def stats(self) -> dict:
        pre = self.prefill.stats()
        dec = self.decode.stats()
        return {
            "disaggregated": True,
            "version": self.version,
            "draining": self._draining,
            "submitted": self.submitted,
            "prefill_replicas": pre["replicas"],
            "decode_replicas": dec["replicas"],
            "replicas": pre["replicas"] + dec["replicas"],
            "replicas_alive": pre["replicas_alive"] + dec["replicas_alive"],
            "slots": pre["slots"] + dec["slots"],
            "active": pre["active"] + dec["active"],
            "waiting": pre["waiting"] + dec["waiting"],
            "handoff": {
                "ok": self.handoffs_ok,
                "miss": self.handoffs_miss,
                "fallbacks": self.fallbacks,
                "d2d": self.handoff_d2d,
                "timeout_s": self.handoff_timeout_s,
                "latency": self._handoff_window.summary(),
            },
            # per-pool phase percentiles: the per-role TTFT/TPOT split
            # (prefill pool TTFT ~= prefill wall; decode pool TTFT ~=
            # handoff-hit admission + first sample)
            "prefill": pre,
            "decode": dec,
        }

    def debug_state(self) -> dict:
        from .metrics.slo import pool_snapshots

        pre = self.prefill.debug_state()
        dec = self.decode.debug_state()
        return {
            "disaggregated": True,
            "draining": self._draining,
            "handoff": {
                "ok": self.handoffs_ok,
                "miss": self.handoffs_miss,
                "fallbacks": self.fallbacks,
                "d2d": self.handoff_d2d,
                "timeout_s": self.handoff_timeout_s,
                "latency": self._handoff_window.summary(),
            },
            # pooled across BOTH role pools (the caller's SLO does not
            # care which pool burned the budget)
            "slo": pool_snapshots(
                [s for s in (pre.get("slo"), dec.get("slo")) if s]
            ) or None,
            "prefill": pre,
            "decode": dec,
        }

    # -- lifecycle ----------------------------------------------------------
    def drain(self) -> None:
        self._draining = True
        self.prefill.drain()
        self.decode.drain()

    def drained(self) -> bool:
        return self.prefill.drained() and self.decode.drained()

    def close(self) -> None:
        self._stop = True
        self._draining = True
        # stop accepting handoff work, let in-flight workers finish their
        # (now fast-failing) submits, then tear the pools down
        self._pool.shutdown(wait=False)
        self.prefill.close()
        self.decode.close()
