"""Auth middleware: basic, API key, OAuth/JWT with JWKS refresh.

Parity: reference middleware/basic_auth.go:18-73, apikey_auth.go:11-58,
oauth.go:53-225 (background JWKS refresh goroutine; per-request RS256 JWT
verification by kid; claims in request context under "JWTClaims";
/.well-known/* routes skip auth, validate.go:5-7).

RS256 verification is pure-stdlib: RSASSA-PKCS1-v1_5 is a modular
exponentiation plus a DigestInfo comparison, so no crypto dependency is
needed for the verify-only path.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.request

from ..request import Request
from ..responder import Response, to_json_bytes
from ..router import WireHandler, ensure_async

_WELL_KNOWN = "/.well-known/"


def _unauthorized(msg: str = "Unauthorized") -> Response:
    return Response(401, [("Content-Type", "application/json")], to_json_bytes({"error": {"message": msg}}))


def _exempt(req: Request) -> bool:
    return req.path.startswith(_WELL_KNOWN) or req.path == "/favicon.ico" or req.method == "OPTIONS"


def basic_auth_middleware(users: dict[str, str] | None = None, validate_func=None):
    """Static user map or custom validator (basic_auth.go:18-73)."""
    if validate_func is not None:
        validate_func = ensure_async(validate_func)

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            if _exempt(req):
                return await next_handler(req)
            header = req.headers.get("authorization", "")
            if not header.startswith("Basic "):
                return _unauthorized()
            try:
                decoded = base64.b64decode(header[6:]).decode("utf-8")
                user, _, password = decoded.partition(":")
            except (ValueError, UnicodeDecodeError):
                return _unauthorized()
            if validate_func is not None:
                ok = await validate_func(user, password)
            else:
                ok = users is not None and hmac.compare_digest(users.get(user, "\x00"), password)
            if not ok:
                return _unauthorized()
            req.context["user"] = user
            return await next_handler(req)

        return h

    return mw


def apikey_auth_middleware(keys: list[str] | None = None, validate_func=None):
    """X-API-KEY header vs key list or validator (apikey_auth.go:11-58)."""
    if validate_func is not None:
        validate_func = ensure_async(validate_func)
    keyset = set(keys or [])

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            if _exempt(req):
                return await next_handler(req)
            key = req.headers.get("x-api-key", "")
            if not key:
                return _unauthorized()
            ok = (await validate_func(key)) if validate_func is not None else key in keyset
            if not ok:
                return _unauthorized()
            return await next_handler(req)

        return h

    return mw


# ---------------- JWT / JWKS ----------------

def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _b64url_to_int(s: str) -> int:
    return int.from_bytes(_b64url_decode(s), "big")


# DigestInfo prefixes for EMSA-PKCS1-v1_5 (RFC 8017 §9.2 notes)
_DIGEST_INFO = {
    "RS256": (hashlib.sha256, bytes.fromhex("3031300d060960864801650304020105000420")),
    "RS384": (hashlib.sha384, bytes.fromhex("3041300d060960864801650304020205000430")),
    "RS512": (hashlib.sha512, bytes.fromhex("3051300d060960864801650304020305000440")),
}


def _rsa_pkcs1_verify(alg: str, n: int, e: int, message: bytes, signature: bytes) -> bool:
    hasher, prefix = _DIGEST_INFO[alg]
    k = (n.bit_length() + 7) // 8
    if len(signature) != k:
        return False
    em = pow(int.from_bytes(signature, "big"), e, n).to_bytes(k, "big")
    digest = hasher(message).digest()
    expected = b"\x00\x01" + b"\xff" * (k - len(prefix) - len(digest) - 3) + b"\x00" + prefix + digest
    return hmac.compare_digest(em, expected)


class JWKSProvider:
    """Fetches and caches a JWKS document, refreshed on an interval by a
    daemon thread (oauth.go:53-71)."""

    def __init__(self, url: str, refresh_interval_s: float = 300.0):
        self.url = url
        self._keys: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.refresh()
        self._thread = threading.Thread(
            target=self._loop, args=(refresh_interval_s,), daemon=True, name="gofr-jwks-refresh"
        )
        self._thread.start()

    def _loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 - keep serving with cached keys
                continue

    def refresh(self) -> None:
        with urllib.request.urlopen(self.url, timeout=5) as resp:  # noqa: S310
            doc = json.loads(resp.read().decode("utf-8"))
        keys = {}
        for k in doc.get("keys", []):
            if k.get("kty") == "RSA" and "kid" in k:
                keys[k["kid"]] = k
        with self._lock:
            self._keys = keys

    def key(self, kid: str) -> dict | None:
        with self._lock:
            return self._keys.get(kid)

    def close(self) -> None:
        self._stop.set()


def verify_jwt(token: str, key_lookup, *, hs_secret: bytes | None = None, leeway_s: float = 30.0) -> dict:
    """Verify a JWT; returns claims. key_lookup(kid) -> JWK dict for RS*;
    hs_secret enables HS256 (symmetric) for self-issued tokens."""
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        header = json.loads(_b64url_decode(header_b64))
        payload = json.loads(_b64url_decode(payload_b64))
        signature = _b64url_decode(sig_b64)
    except (ValueError, json.JSONDecodeError) as e:
        raise PermissionError("malformed token") from e
    alg = header.get("alg", "")
    signing_input = f"{header_b64}.{payload_b64}".encode()
    if alg in _DIGEST_INFO:
        kid = header.get("kid", "")
        jwk = key_lookup(kid) if key_lookup else None
        if jwk is None:
            raise PermissionError("unknown key id")
        n, e = _b64url_to_int(jwk["n"]), _b64url_to_int(jwk["e"])
        if not _rsa_pkcs1_verify(alg, n, e, signing_input, signature):
            raise PermissionError("bad signature")
    elif alg == "HS256" and hs_secret is not None:
        expected = hmac.new(hs_secret, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, signature):
            raise PermissionError("bad signature")
    else:
        raise PermissionError(f"unsupported alg {alg}")
    now = time.time()
    try:
        if "exp" in payload and now > float(payload["exp"]) + leeway_s:
            raise PermissionError("token expired")
        if "nbf" in payload and now < float(payload["nbf"]) - leeway_s:
            raise PermissionError("token not yet valid")
    except (TypeError, ValueError) as e:
        raise PermissionError("malformed time claim") from e
    return payload


def oauth_middleware(jwks: JWKSProvider | None = None, *, hs_secret: bytes | None = None):
    """Bearer-JWT auth; claims land in req.context['JWTClaims'] (oauth.go:107-152)."""

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            if _exempt(req):
                return await next_handler(req)
            header = req.headers.get("authorization", "")
            if not header.startswith("Bearer "):
                return _unauthorized()
            try:
                claims = verify_jwt(header[7:], jwks.key if jwks else None, hs_secret=hs_secret)
            except PermissionError as e:
                return _unauthorized(str(e))
            req.context["JWTClaims"] = claims
            return await next_handler(req)

        return h

    return mw
