"""Default + auth middleware for the HTTP server.

Parity: reference pkg/gofr/http/middleware/ — chain order
Tracer -> Logging -> CORS -> Metrics (router.go:23-28), panic recovery and
request logging (logger.go), metrics by route template (metrics.go),
basic/api-key/oauth auth (basic_auth.go, apikey_auth.go, oauth.go).
"""

from .core import cors_middleware, logging_middleware, metrics_middleware, tracer_middleware
from .auth import apikey_auth_middleware, basic_auth_middleware, oauth_middleware

__all__ = [
    "apikey_auth_middleware",
    "basic_auth_middleware",
    "cors_middleware",
    "logging_middleware",
    "metrics_middleware",
    "oauth_middleware",
    "tracer_middleware",
]
