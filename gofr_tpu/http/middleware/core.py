"""Tracer, logging (with recovery), CORS, metrics middleware.

Parity: reference middleware/tracer.go:15-32, logger.go:69-152, cors.go:6-23,
metrics.go:21-41.
"""

from __future__ import annotations

import io
import time
import traceback

from ...logging import Logger
from ...metrics import Manager
from ...tracing import Tracer
from ..request import Request
from ..responder import Response, to_json_bytes
from ..router import WireHandler


class RequestLog:
    """Structured request log (middleware/logger.go:27-60)."""

    __slots__ = ("trace_id", "span_id", "start_time", "response_time_us", "method", "uri", "response_code", "remote_addr")

    def __init__(self, trace_id, span_id, start_time, response_time_us, method, uri, response_code, remote_addr):
        self.trace_id = trace_id
        self.span_id = span_id
        self.start_time = start_time
        self.response_time_us = response_time_us
        self.method = method
        self.uri = uri
        self.response_code = response_code
        self.remote_addr = remote_addr

    def to_log_dict(self):
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "start_time": self.start_time,
            "response_time": self.response_time_us,
            "method": self.method,
            "uri": self.uri,
            "response_code": self.response_code,
            "remote_addr": self.remote_addr,
        }

    def pretty_print(self, writer: io.TextIOBase) -> None:
        color = 32 if self.response_code < 400 else (33 if self.response_code < 500 else 31)
        writer.write(
            f"\x1b[38;5;8m{self.trace_id}\x1b[0m "
            f"\x1b[{color}m{self.response_code}\x1b[0m "
            f"{self.response_time_us:>10}µs {self.method} {self.uri}"
        )


def tracer_middleware(tracer: Tracer):
    """Extract W3C traceparent, open a span named 'METHOD /path' (the
    template isn't known yet — tracing runs outermost, before route match)."""

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            span = tracer.start_span(
                f"{req.method} {req.path}",
                traceparent=req.headers.get("traceparent"),
            )
            req.context["span"] = span
            try:
                resp = await next_handler(req)
                span.set_attribute("http.status_code", resp.status)
                if resp.status >= 500:
                    span.set_status("ERROR")
                return resp
            finally:
                span.end()

        return h

    return mw


def logging_middleware(logger: Logger):
    """Request log + panic recovery -> 500 envelope (logger.go:69-152).
    Surfaces the trace id to clients as X-Correlation-ID (logger.go:77-79)."""

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            start = time.perf_counter()
            span = req.context.get("span")
            trace_id = span.trace_id if span else ""
            span_id = span.span_id if span else ""
            try:
                resp = await next_handler(req)
            except Exception:  # noqa: BLE001 - recovery boundary
                logger.error(f"panic recovered: {traceback.format_exc()}")
                resp = Response(
                    500,
                    [("Content-Type", "application/json")],
                    to_json_bytes({"error": {"message": "some unexpected error has occurred"}}),
                )
            elapsed_us = int((time.perf_counter() - start) * 1e6)
            if trace_id:
                resp.headers.append(("X-Correlation-ID", trace_id))
            log = RequestLog(
                trace_id, span_id,
                time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                elapsed_us, req.method, req.target, resp.status, req.remote_addr,
            )
            if resp.status >= 500:
                logger.error(log)
            else:
                logger.info(log)
            return resp

        return h

    return mw


def cors_middleware(overrides: dict[str, str] | None = None):
    """Wildcard CORS + preflight short-circuit (cors.go:6-23). Headers
    overridable via config (ACCESS_CONTROL_ALLOW_* env, as the reference's
    docs describe)."""
    headers = {
        "Access-Control-Allow-Origin": "*",
        "Access-Control-Allow-Headers": "Authorization, Content-Type, x-requested-with, origin, true-client-ip, X-Correlation-ID",
    }
    if overrides:
        headers.update(overrides)

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            if req.method == "OPTIONS":
                hs = [*headers.items(), ("Access-Control-Allow-Methods", "GET, POST, PUT, PATCH, DELETE, OPTIONS")]
                return Response(200, hs, b"")
            resp = await next_handler(req)
            resp.headers.extend(headers.items())
            return resp

        return h

    return mw


def metrics_middleware(manager: Manager):
    """app_http_response histogram labeled by route template (metrics.go:21-41)."""

    def mw(next_handler: WireHandler) -> WireHandler:
        async def h(req: Request) -> Response:
            start = time.perf_counter()
            resp = await next_handler(req)
            manager.record_histogram(
                "app_http_response",
                time.perf_counter() - start,
                path=req.route_template,
                method=req.method,
                status=str(resp.status),
            )
            return resp

        return h

    return mw
