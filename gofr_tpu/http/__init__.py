"""HTTP plumbing: server, router, request/responder, middleware, errors.

Parity: reference pkg/gofr/http/ (router.go, request.go, responder.go,
errors.go, middleware/*). TPU-first difference: the server is a single
asyncio event loop rather than a thread-per-connection model, because the
dynamic batcher (gofr_tpu/batching) coalesces concurrent in-flight requests
into one device execution — requests must be cheap cooperative tasks, not
threads.
"""

from .errors import (
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorInvalidRoute,
    ErrorMissingParam,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
    ErrorServiceUnavailable,
    HTTPError,
)
from .request import Request
from .responder import FileResponse, Raw, Redirect, Response

__all__ = [
    "ErrorEntityNotFound",
    "ErrorInvalidParam",
    "ErrorInvalidRoute",
    "ErrorMissingParam",
    "ErrorPanicRecovery",
    "ErrorRequestTimeout",
    "ErrorServiceUnavailable",
    "FileResponse",
    "HTTPError",
    "Raw",
    "Redirect",
    "Request",
    "Response",
]
