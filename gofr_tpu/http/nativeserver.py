"""Protocol-mode HTTP/1.1 server over the native C++ wire codec.

Parity: reference pkg/gofr/httpServer.go:19-50 — same observable behavior
as gofr_tpu/http/server.py (AsyncHTTPServer): keep-alive, chunked request
bodies, Expect: 100-continue, HEAD, chunked streaming responses, 5 s
read-header timeout, 64 KiB header cap, 100 MB body cap, identical error
envelopes. Re-designed transport: instead of asyncio streams (whose
readuntil/readexactly layers dominate per-request CPU), connections are
asyncio.Protocol instances feeding a byte buffer into `_gofr_http.parse`
(gofr_tpu/native/httpcore.cc) and writing responses serialized by
`build_head` in a single transport.write. The reference's HTTP plane is
compiled Go; this is the equivalent native fast path for the CPU-bound
configs, with AsyncHTTPServer as the always-available pure-Python
fallback (App picks at startup; GOFR_HTTP_NATIVE=0 forces the fallback).

Request dispatch, routing, and middleware stay 100% Python and identical
between the two servers — tests/test_native_http.py runs the same
conformance suite against both.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..logging import Logger
from ..native import load_http_codec
from .request import Request
from .responder import Response
from .server import _clean_header, _status_line  # shared with server.py

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 100 * 1024 * 1024  # server.py parity
READ_HEADER_TIMEOUT = 5.0  # httpServer.go:37
KEEPALIVE_IDLE_TIMEOUT = 75.0
# receive-side high-water mark: while a request is processing, a client
# streaming ahead (pipelining/flooding) is paused once this much is
# buffered — the streams server gets the same protection from asyncio
# flow control; without this the protocol server would buffer unbounded
RECV_HIGH_WATER = 256 * 1024

_ERR_HEAD = b"Content-Type: application/json\r\nConnection: close\r\n"


def _py_serialize(
    resp: Response, body: bytes, close: bool, chunked: bool = False
) -> bytes:
    """Tolerant fallback serializer with server.py's f-string semantics,
    used when the strict C serializer rejects exotic header values.
    chunked=True emits a streaming head (Transfer-Encoding, no body)."""
    head = [_status_line(resp.status)]
    seen = set()
    for k, v in resp.headers:
        ck = _clean_header(k)
        seen.add(ck.lower())
        head.append(f"{ck}: {_clean_header(v)}\r\n".encode("latin-1"))
    if close:
        head.append(b"Connection: close\r\n")
    if chunked:
        if "transfer-encoding" not in seen:
            head.append(b"Transfer-Encoding: chunked\r\n")
    elif "content-length" not in seen:
        head.append(f"Content-Length: {len(resp.body)}\r\n".encode())
    head.append(b"\r\n")
    return b"".join(head) + body


class _HTTPProtocol(asyncio.Protocol):
    """One connection: buffer -> native parse -> dispatch -> native head."""

    __slots__ = (
        "server", "codec", "transport", "buf", "head", "remote",
        "processing", "closed", "timer", "paused_reading", "can_write",
        "chunk_parts", "chunk_len", "_loop",
    )

    def __init__(self, server: "NativeHTTPServer"):
        self.server = server
        self.codec = server.codec
        self.transport: asyncio.Transport | None = None
        self.buf = bytearray()
        self.head = None  # parsed tuple awaiting its body
        self.remote = ""
        self.processing = False
        self.closed = False
        self.paused_reading = False
        self.chunk_parts: list[bytes] | None = None  # incremental chunked body
        self.chunk_len = 0
        self.timer: asyncio.TimerHandle | None = None
        self.can_write: asyncio.Event | None = None  # created lazily (streams)
        self._loop = server._loop

    # ---- transport callbacks -------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport
        peer = transport.get_extra_info("peername")
        self.remote = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else ""
        self._arm_timer(READ_HEADER_TIMEOUT)

    def connection_lost(self, exc) -> None:
        self.closed = True
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None
        if self.can_write is not None:
            self.can_write.set()  # unblock a draining stream writer

    def pause_writing(self) -> None:
        if self.can_write is None:
            self.can_write = asyncio.Event()
        self.can_write.clear()

    def resume_writing(self) -> None:
        if self.can_write is not None:
            self.can_write.set()

    def data_received(self, data: bytes) -> None:
        self.buf += data
        if self.processing:
            if len(self.buf) > RECV_HIGH_WATER and not self.paused_reading:
                self.paused_reading = True
                self.transport.pause_reading()
            return
        self._pump()

    # ---- timers ---------------------------------------------------------
    def _arm_timer(self, timeout: float) -> None:
        if self.timer is not None:
            self.timer.cancel()
        self.timer = self._loop.call_later(timeout, self._on_timeout)

    def _on_timeout(self) -> None:
        self.timer = None
        if not self.processing and self.transport is not None:
            self.transport.close()

    # ---- request assembly ----------------------------------------------
    def _pump(self) -> None:
        """Parse as many complete requests as the buffer holds (one at a
        time — the next parse happens after the current response)."""
        if self.closed or self.transport is None:
            return
        try:
            if self.head is None:
                parsed = self.codec.parse(self.buf)
                if parsed is None:
                    if len(self.buf) > MAX_HEADER_BYTES:
                        self._protocol_error(431, "headers too large")
                    return
                if parsed[0] > MAX_HEADER_BYTES:
                    self._protocol_error(431, "headers too large")
                    return
                self.head = parsed
                # header block complete: body reads are not timed (streams
                # server parity — its wait_for wraps _read_headers only)
                if self.timer is not None:
                    self.timer.cancel()
                    self.timer = None
                if parsed[6] & self.codec.F_CHUNKED:
                    self.chunk_parts = []
                    self.chunk_len = 0
                if parsed[6] & self.codec.F_EXPECT_CONTINUE:
                    self.transport.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            end, method, target, minor, headers, clen, flags = self.head
            if flags & self.codec.F_CHUNKED:
                # incremental: consume complete chunks NOW and drop their
                # encoded bytes from the buffer, so a large upload arriving
                # in many segments is parsed once (O(n)), not re-scanned
                # from scratch per data_received
                data, new_off, done = self.codec.parse_chunked_step(self.buf, end)
                if data:
                    self.chunk_parts.append(data)
                    self.chunk_len += len(data)
                    if self.chunk_len > MAX_BODY_BYTES:
                        raise ValueError(413, "body too large")
                if new_off > end:
                    del self.buf[end:new_off]
                if not done:
                    return
                body = b"".join(self.chunk_parts)
                self.chunk_parts = None
                consumed = end
            elif clen > 0:
                if len(self.buf) - end < clen:
                    return
                body = bytes(self.buf[end : end + clen])
                consumed = end + clen
            else:
                body = b""
                consumed = end
        except ValueError as e:
            if len(e.args) == 2 and isinstance(e.args[0], int):
                status, msg = e.args
            else:
                status, msg = 400, "bad request"
            self._protocol_error(status, msg)
            return

        del self.buf[:consumed]
        self.head = None
        # server.py parity: HTTP/1.0 always closes (even with an explicit
        # keep-alive header — the pure-Python server ignores it too)
        close = bool(flags & self.codec.F_CLOSE) or minor == 0
        req = Request(method, target, headers, body, self.remote)
        self.processing = True
        self._loop.create_task(self._respond(req, method, close))

    def _protocol_error(self, status: int, msg: str) -> None:
        if self.transport is None:
            return
        body = ('{"error":{"message":"' + msg + '"}}').encode()
        self.transport.write(
            _status_line(status)
            + _ERR_HEAD
            + f"Content-Length: {len(body)}\r\n\r\n".encode()
            + body
        )
        self.transport.close()
        self.closed = True

    # ---- response -------------------------------------------------------
    async def _respond(self, req: Request, method: str, close: bool) -> None:
        try:
            try:
                resp = await self.server.dispatch(req)
            except Exception as e:  # noqa: BLE001 - middleware recovers first
                if self.server.logger:
                    self.server.logger.error(f"unhandled dispatch error: {e!r}")
                resp = Response(
                    500,
                    [("Content-Type", "application/json")],
                    b'{"error":{"message":"internal error"}}',
                )
            if self.closed or self.transport is None:
                # client vanished while the handler ran: the response is
                # undeliverable, but a streamed body still owns resources
                # (engine slot, a proxy's in-flight permit + upstream
                # socket) — close the producer instead of dropping it
                await self._aclose_stream(resp)
                return
            try:
                if resp.stream is not None and method != "HEAD":
                    ok = await self._write_stream(resp, close)
                    if not ok:
                        return
                else:
                    body = b"" if method == "HEAD" else resp.body
                    try:
                        # HEAD advertises the real entity length (server.py
                        # parity)
                        out = self.codec.build_head(
                            resp.status, resp.headers, len(resp.body),
                            1 if close else 0, 0,
                            body if body else None,
                        )
                    except Exception:
                        # the C serializer is strict (2-tuples of str); the
                        # streams server stringifies anything — match it so
                        # the same handler works under either server
                        out = _py_serialize(resp, body, close)
                    self.transport.write(out)
                    # drain: a pipelining client that reads slowly must not
                    # grow the transport buffer unbounded (server.py awaits
                    # writer.drain() after every response)
                    if self.can_write is not None and not self.can_write.is_set():
                        await self.can_write.wait()
                        if self.closed:
                            return
            except Exception as e:  # noqa: BLE001 - never leave a hung conn
                if self.server.logger:
                    self.server.logger.error(f"response write failed: {e!r}")
                if self.transport is not None:
                    self.transport.abort()
                self.closed = True
                return
            if close:
                self.transport.close()
                self.closed = True
                return
        finally:
            self.processing = False
            if self.paused_reading and self.transport is not None and not self.closed:
                self.paused_reading = False
                self.transport.resume_reading()
        self._arm_timer(KEEPALIVE_IDLE_TIMEOUT)
        if self.buf:
            self._pump()  # pipelined request already buffered

    async def _write_stream(self, resp: Response, close: bool) -> bool:
        """Chunked streaming response with transport flow control.
        Returns False when the connection is dead (caller stops serving)."""
        assert self.transport is not None
        try:
            head = self.codec.build_head(
                resp.status, resp.headers, -1, 1 if close else 0, 1
            )
        except Exception:
            # strict C serializer rejected a header (exotic type or CR/LF
            # taint) — sanitize and serialize in Python like the non-stream
            # fallback, so both servers serve the stream instead of aborting
            head = _py_serialize(resp, b"", close, chunked=True)
        self.transport.write(head)
        try:
            async for chunk in resp.stream:
                if not chunk:
                    continue
                if self.closed:
                    # peer hung up (connection_lost): close the producer
                    # NOW — its GeneratorExit path is where a streaming
                    # LLM handler cancels the GenRequest (slot freed,
                    # finish_reason "disconnect") instead of decoding to
                    # completion for a dead connection
                    await self._aclose_stream(resp)
                    return False
                self.transport.write(
                    f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                )
                if self.can_write is not None and not self.can_write.is_set():
                    await self.can_write.wait()
                    if self.closed:
                        await self._aclose_stream(resp)
                        return False
        except Exception as e:  # noqa: BLE001
            # Mid-stream failure: abort WITHOUT the chunked terminator so the
            # client sees truncation, not a silently-short success (server.py
            # semantics).
            if self.server.logger:
                self.server.logger.error(f"stream aborted: {e!r}")
            self.transport.abort()
            self.closed = True
            await self._aclose_stream(resp)
            return False
        self.transport.write(b"0\r\n\r\n")
        return True

    @staticmethod
    async def _aclose_stream(resp: Response) -> None:
        """Close an abandoned body generator so handler-side cleanup
        (GenRequest disconnect-cancel) runs immediately, not at GC."""
        aclose = getattr(resp.stream, "aclose", None)
        if aclose is not None:
            try:
                await aclose()
            except Exception:  # noqa: BLE001 — teardown must not mask the abort
                pass


class NativeHTTPServer:
    """Drop-in alternative to AsyncHTTPServer backed by the C++ codec.

    Construction requires the codec: callers use `available()` (or let
    gofr_tpu.app.App decide) and fall back to AsyncHTTPServer otherwise.
    """

    def __init__(
        self,
        dispatch: Callable,
        port: int = 8000,
        host: str = "0.0.0.0",
        logger: Logger | None = None,
        tls=None,
    ):
        codec = load_http_codec()
        if codec is None:
            raise RuntimeError("native HTTP codec unavailable")
        self.codec = codec
        self.dispatch = dispatch  # async (Request) -> Response
        self.port = port
        self.host = host
        self.logger = logger
        self.reuse_port = False
        self.tls = tls  # server-side ssl.SSLContext (HTTPS); see server.py
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    @staticmethod
    def available() -> bool:
        return load_http_codec() is not None

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._server = await self._loop.create_server(
            lambda: _HTTPProtocol(self),
            self.host,
            self.port,
            reuse_port=self.reuse_port or None,
            ssl=self.tls,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.logger:
            scheme = "HTTPS" if self.tls is not None else "HTTP"
            self.logger.info(
                f"{scheme} server (native codec) listening on :{self.port}"
            )

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        await self._server.serve_forever()

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
