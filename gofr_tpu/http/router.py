"""Router: method+path dispatch with {param} segments and middleware chain.

Parity: reference pkg/gofr/http/router.go:14-49 (gorilla/mux wrapper with
default middleware chain Tracer->Logging->CORS->Metrics, user middleware via
UseMiddleware). Re-designed: a static-route hash fast path plus a segment
trie, because route match is on the serving hot path in front of the batcher.

Route templates use ``{name}`` segments (e.g. ``/users/{id}``) and a trailing
``{rest...}`` catch-all. The matched template (not the URL) is used as the
metrics label to avoid cardinality bombs (middleware/metrics.go:21-41);
unmatched requests are labeled with the UNMATCHED constant for the same
reason.
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable

from .request import Request
from .responder import Response

# A wire handler: async (Request) -> Response
WireHandler = Callable[[Request], Awaitable[Response]]
# Middleware: (WireHandler) -> WireHandler
Middleware = Callable[[WireHandler], WireHandler]

# route_template label for requests that matched no route (cardinality guard)
UNMATCHED = "/__unmatched__"


class _Route:
    """One registered (method, template) endpoint at a trie leaf."""

    __slots__ = ("handler", "template", "param_names")

    def __init__(self, handler: WireHandler, template: str, param_names: list[str]):
        self.handler = handler
        self.template = template
        self.param_names = param_names


class _Node:
    __slots__ = ("children", "param_child", "wild_routes", "routes")

    def __init__(self):
        self.children: dict[str, _Node] = {}
        self.param_child: _Node | None = None
        self.wild_routes: dict[str, _Route] = {}  # method -> catch-all route
        self.routes: dict[str, _Route] = {}  # method -> route


async def _default_404(_req: Request) -> Response:
    from .responder import to_json_bytes

    return Response(404, [("Content-Type", "application/json")], to_json_bytes({"error": {"message": "route not registered"}}))


async def _default_405(_req: Request) -> Response:
    from .responder import to_json_bytes

    return Response(405, [("Content-Type", "application/json")], to_json_bytes({"error": {"message": "method not allowed"}}))


class Router:
    def __init__(self):
        self._static: dict[tuple[str, str], _Route] = {}
        self._static_paths: set[str] = set()
        self._root = _Node()
        self._middleware: list[Middleware] = []
        self._built = False
        self.not_found: WireHandler = _default_404
        self.method_not_allowed: WireHandler = _default_405

    def use(self, mw: Middleware) -> None:
        """Append middleware. Applied outermost-first in registration order."""
        if self._built:
            raise RuntimeError("cannot add middleware after server start")
        self._middleware.append(mw)

    def has(self, method: str, template: str) -> bool:
        """Is a handler already bound to this exact static route? Lets
        late built-in registration yield to an earlier explicit binding
        (the front router rebinds a well-known path to its fleet-fan
        variant before serve())."""
        template = "/" + template.strip("/") if template.strip("/") else "/"
        return (method.upper(), template) in self._static

    def add(self, method: str, template: str, handler: WireHandler) -> None:
        if self._built:
            raise RuntimeError("cannot add routes after server start")
        method = method.upper()
        template = "/" + template.strip("/") if template.strip("/") else "/"
        if "{" not in template:
            self._static[(method, template)] = _Route(handler, template, [])
            self._static_paths.add(template)
            return
        param_names: list[str] = []
        node = self._root
        segs = template.strip("/").split("/")
        for i, seg in enumerate(segs):
            if seg.startswith("{") and seg.endswith("...}"):
                if i != len(segs) - 1:
                    raise ValueError(f"catch-all segment must be last: {template}")
                param_names.append(seg[1:-4])
                node.wild_routes[method] = _Route(handler, template, param_names)
                return
            if seg.startswith("{") and seg.endswith("}"):
                param_names.append(seg[1:-1])
                if node.param_child is None:
                    node.param_child = _Node()
                node = node.param_child
            else:
                node = node.children.setdefault(seg, _Node())
        node.routes[method] = _Route(handler, template, param_names)

    def routes(self) -> list[tuple[str, str]]:
        out = [(m, p) for (m, p) in self._static]
        stack = [self._root]
        while stack:
            n = stack.pop()
            for m, r in n.routes.items():
                out.append((m, r.template))
            for m, r in n.wild_routes.items():
                out.append((m, r.template))
            stack.extend(n.children.values())
            if n.param_child:
                stack.append(n.param_child)
        return sorted(out)

    def _match(self, method: str, path: str) -> tuple[_Route | None, list[str], bool]:
        """-> (route, param_values, path_exists_under_other_method)."""
        r = self._static.get((method, path))
        if r is not None:
            return r, [], True
        path_exists = path in self._static_paths

        node = self._root
        values: list[str] = []
        segs = path.strip("/").split("/") if path != "/" else [""]
        for i, seg in enumerate(segs):
            if node.wild_routes:
                rest = "/".join(segs[i:])
                wr = node.wild_routes.get(method)
                if wr is not None:
                    return wr, [*values, rest], True
                return None, [], True
            nxt = node.children.get(seg)
            if nxt is None and node.param_child is not None and seg != "":
                values.append(seg)
                nxt = node.param_child
            if nxt is None:
                return None, [], path_exists
            node = nxt
        if node.routes:
            r = node.routes.get(method)
            if r is None:
                return None, [], True
            return r, values, True
        if node.wild_routes:
            wr = node.wild_routes.get(method)
            if wr is not None:
                return wr, [*values, ""], True
            return None, [], True
        return None, [], path_exists

    def build(self) -> None:
        """Wrap every route handler in the middleware chain once, at startup."""
        if self._built:
            return
        self._built = True

        def wrap(h: WireHandler) -> WireHandler:
            for mw in reversed(self._middleware):
                h = mw(h)
            return h

        for r in self._static.values():
            r.handler = wrap(r.handler)
        stack = [self._root]
        while stack:
            n = stack.pop()
            for r in n.routes.values():
                r.handler = wrap(r.handler)
            for r in n.wild_routes.values():
                r.handler = wrap(r.handler)
            stack.extend(n.children.values())
            if n.param_child:
                stack.append(n.param_child)
        # 404/405 go through middleware too (logging + metrics see them)
        self.not_found = wrap(self.not_found)
        self.method_not_allowed = wrap(self.method_not_allowed)

    async def dispatch(self, req: Request) -> Response:
        if not self._built:
            self.build()
        route, values, path_exists = self._match(req.method, req.path)
        if route is None:
            req.route_template = UNMATCHED
            if req.method == "OPTIONS" or not path_exists:
                return await self.not_found(req)
            return await self.method_not_allowed(req)
        req.path_params = dict(zip(route.param_names, values))
        req.route_template = route.template
        return await route.handler(req)


def ensure_async(fn: Callable[..., Any]) -> Callable[..., Awaitable[Any]]:
    """Adapt a sync callable to async by running it in the default executor."""
    if asyncio.iscoroutinefunction(fn):
        return fn

    async def runner(*args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, lambda: fn(*args, **kwargs))

    return runner
