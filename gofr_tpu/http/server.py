"""Asyncio HTTP/1.1 server with keep-alive and chunked streaming responses.

Parity: reference pkg/gofr/httpServer.go:19-50 (server with read-header
timeout + graceful shutdown). Re-designed for the TPU serving model: one
event loop, cooperative request tasks feeding the dynamic batcher; a request
"goroutine" here is an asyncio task whose await point is a batch future.

Protocol support: request line + headers (64 KiB cap), Content-Length and
chunked request bodies, keep-alive, HEAD, Expect: 100-continue, chunked
streaming responses (for token streams), Connection: close handling.
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..logging import Logger
from .request import Request
from .responder import Response

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 100 * 1024 * 1024  # matches the reference's 100MB zip cap spirit
READ_HEADER_TIMEOUT = 5.0  # httpServer.go:37


class HTTPProtocolError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _cl_value(digits: str) -> int:
    """Parse an all-digits Content-Length, clamped at MAX_BODY_BYTES+1 like
    the native codec (every oversized value means the same thing: too
    large). The length pre-check keeps a multi-KB digit string from
    tripping CPython's int-conversion digit limit (uncaught ValueError)."""
    s = digits.lstrip("0")
    if len(s) > 15:
        return MAX_BODY_BYTES + 1
    return min(int(s or "0"), MAX_BODY_BYTES + 1)


def _clean_header(s: object) -> str:
    """Strip CR/LF/NUL so a handler echoing untrusted input into a response
    header cannot split the response (Go's net/http sanitizes these too)."""
    s = str(s)
    if "\r" in s or "\n" in s or "\x00" in s:
        return s.replace("\r", "").replace("\n", "").replace("\x00", "")
    return s


async def _read_headers(reader: asyncio.StreamReader) -> tuple[str, str, str, dict[str, str]] | None:
    """Read request line + headers. Returns None on clean EOF between requests."""
    try:
        block = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise HTTPProtocolError(400, "truncated request") from e
    except asyncio.LimitOverrunError as e:
        raise HTTPProtocolError(431, "headers too large") from e
    lines = block.decode("latin-1").split("\r\n")
    # a CR surviving the CRLF split is a bare CR (RFC 9112 2.2) — parsers
    # that treat it as a terminator would frame this head differently
    for line in lines:
        if "\r" in line:
            raise HTTPProtocolError(400, "bare CR in header")
    request_line = lines[0]
    parts = request_line.split(" ")
    if len(parts) != 3:
        raise HTTPProtocolError(400, "malformed request line")
    method, target, version = parts
    # bounds mirror the native codec exactly (tests/test_native_http.py
    # fuzzes the two parsers against each other): non-empty method <= 31
    # chars, non-empty target, version HTTP/1.<minor> with a minor digit
    if not method or len(method) > 31 or not target:
        raise HTTPProtocolError(400, "malformed request line")
    if not version.startswith("HTTP/1.") or len(version) < 8:
        raise HTTPProtocolError(505, "http version not supported")
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        # obs-fold (RFC 7230 3.2.4): a continuation line would otherwise
        # parse as a fresh header and desync against proxies that unfold
        if line[0] in " \t":
            raise HTTPProtocolError(400, "obsolete line folding")
        if ":" not in line:
            raise HTTPProtocolError(400, "malformed header")
        k, _, v = line.partition(":")
        k = k.strip()
        if not k:  # RFC 9112: field names are non-empty tokens
            raise HTTPProtocolError(400, "malformed header")
        k = k.lower()
        v = v.strip()
        # duplicate Content-Length with a different value is a smuggling
        # vector (proxies disagree on which wins) -> hard 400. Compare
        # PARSED values, clamped at the cap, mirroring the native codec
        # ("5" vs "05" is not a conflict; two oversized values both mean
        # "too large" and 413 later).
        if k == "content-length":
            # digits only, validated per-line like the native codec ('+5',
            # '5_0' etc. must not frame a body a strict peer rejects)
            if not (v.isascii() and v.isdigit()):
                raise HTTPProtocolError(400, "bad content-length")
            if k in headers and headers[k] != v:
                if _cl_value(headers[k]) != _cl_value(v):
                    raise HTTPProtocolError(400, "conflicting content-length")
        # the FINAL transfer coding must be chunked or the body length is
        # undefined (RFC 7230 3.3.3); checked per-line like the native
        # codec so a smuggled first line can't hide behind dict last-wins
        if k == "transfer-encoding":
            last = v.rsplit(",", 1)[-1].strip()
            if last.lower() != "chunked":
                raise HTTPProtocolError(400, "unsupported transfer-encoding")
        headers[k] = v
    # Transfer-Encoding and Content-Length together is the canonical
    # request-smuggling ambiguity -> reject
    if "transfer-encoding" in headers and "content-length" in headers:
        raise HTTPProtocolError(400, "content-length with transfer-encoding")
    return method.upper(), target, version, headers


async def _read_body(reader: asyncio.StreamReader, headers: dict[str, str]) -> bytes:
    te = headers.get("transfer-encoding", "").lower()
    if "chunked" in te:
        chunks: list[bytes] = []
        total = 0
        while True:
            size_line = await reader.readline()
            hexpart = size_line.strip().split(b";")[0]
            # strict hex only — int(x, 16) also accepts '0x10', '1_0' and
            # '-5' (negative would crash readexactly), none of which the
            # native codec or an RFC-strict peer frames the same way
            if not hexpart or any(
                c not in b"0123456789abcdefABCDEF" for c in hexpart
            ):
                raise HTTPProtocolError(400, "bad chunk size")
            size = int(hexpart, 16)
            if size == 0:
                # trailers until blank line
                while (await reader.readline()).strip():
                    pass
                break
            total += size
            if total > MAX_BODY_BYTES:
                raise HTTPProtocolError(413, "body too large")
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # CRLF
        return b"".join(chunks)
    cl = headers.get("content-length")
    if cl is None:
        return b""
    if not (cl.isascii() and cl.isdigit()):
        raise HTTPProtocolError(400, "bad content-length")
    # clamped parse: a huge digit string means "too large" (413), and must
    # not trip CPython's int-conversion digit limit (native codec parity)
    n = _cl_value(cl)
    if n > MAX_BODY_BYTES:
        raise HTTPProtocolError(413, "body too large")
    if n == 0:
        return b""
    try:
        return await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        raise HTTPProtocolError(400, "truncated body") from e


_STATUS_TEXT = {
    200: "OK", 201: "Created", 204: "No Content", 301: "Moved Permanently", 302: "Found",
    400: "Bad Request", 401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout", 413: "Payload Too Large",
    429: "Too Many Requests", 431: "Request Header Fields Too Large",
    500: "Internal Server Error", 503: "Service Unavailable", 505: "HTTP Version Not Supported",
}


def _status_line(status: int) -> bytes:
    return f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Status')}\r\n".encode("latin-1")


class AsyncHTTPServer:
    def __init__(
        self,
        dispatch: Callable,
        port: int = 8000,
        host: str = "0.0.0.0",
        logger: Logger | None = None,
        tls=None,
    ):
        self.dispatch = dispatch  # async (Request) -> Response
        self.port = port
        self.host = host
        self.logger = logger
        # SO_REUSEPORT bind: lets N worker processes share the port with
        # kernel-level connection balancing (App multi-worker mode)
        self.reuse_port = False
        # tls: server-side ssl.SSLContext (HTTPS). The reference terminates
        # TLS at the ingress; this is the standalone-deployment escape hatch
        self.tls = tls
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_HEADER_BYTES,
            reuse_port=self.reuse_port or None, ssl=self.tls,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.logger:
            scheme = "HTTPS" if self.tls is not None else "HTTP"
            self.logger.info(f"{scheme} server listening on :{self.port}")

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        await self._server.serve_forever()

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        remote = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else ""
        try:
            first_request = True
            while True:
                try:
                    timeout = READ_HEADER_TIMEOUT if first_request else 75.0  # keep-alive idle
                    head = await asyncio.wait_for(_read_headers(reader), timeout=timeout)
                except asyncio.TimeoutError:
                    return
                if head is None:
                    return
                first_request = False
                method, target, version, headers = head
                if headers.get("expect", "").lower() == "100-continue":
                    writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                    await writer.drain()
                body = await _read_body(reader, headers)
                req = Request(method, target, headers, body, remote)
                try:
                    resp = await self.dispatch(req)
                except Exception as e:  # noqa: BLE001 - last-resort; middleware recovers first
                    if self.logger:
                        self.logger.error(f"unhandled dispatch error: {e!r}")
                    resp = Response(500, [("Content-Type", "application/json")], b'{"error":{"message":"internal error"}}')
                close = (
                    headers.get("connection", "").lower() == "close"
                    or version == "HTTP/1.0"
                )
                await self._write_response(writer, resp, method, close)
                if close:
                    return
        except HTTPProtocolError as e:
            try:
                body = ('{"error":{"message":"' + e.message + '"}}').encode()
                writer.write(
                    _status_line(e.status)
                    + b"Content-Type: application/json\r\nConnection: close\r\n"
                    + f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body
                )
                await writer.drain()
            except (ConnectionError, RuntimeError):
                pass
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, RuntimeError):
                pass

    async def _write_response(
        self, writer: asyncio.StreamWriter, resp: Response, method: str, close: bool
    ) -> None:
        head = [_status_line(resp.status)]
        # 'seen' must reflect the names as EMITTED (post-sanitization) or a
        # CR/LF-bearing name could coexist with the auto-added framing line
        seen = set()
        for k, v in resp.headers:
            ck = _clean_header(k)
            seen.add(ck.lower())
            head.append(f"{ck}: {_clean_header(v)}\r\n".encode("latin-1"))
        if close:
            head.append(b"Connection: close\r\n")
        if resp.stream is not None and method != "HEAD":
            if "transfer-encoding" not in seen:
                head.append(b"Transfer-Encoding: chunked\r\n")
            head.append(b"\r\n")
            try:
                # header write INSIDE the try: a client gone before the
                # first byte must still run the except path below, which
                # closes the producing generator — an un-started
                # generator's finally never runs, so dropping it here
                # would leak the producer (and whatever it holds: an
                # engine slot, a proxy's upstream socket)
                writer.write(b"".join(head))
                await writer.drain()
                async for chunk in resp.stream:
                    if not chunk:
                        continue
                    # a peer that hung up surfaces as connection_lost on
                    # the transport before the next write fails — check
                    # it per chunk so a dead client is detected at the
                    # next produced token, not at stream end
                    if writer.transport.is_closing():
                        raise ConnectionError("client disconnected")
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    await writer.drain()
            except Exception as e:  # noqa: BLE001
                # Mid-stream failure: do NOT write the chunked terminator —
                # abort the connection so the client sees truncation instead
                # of a syntactically-complete (but silently short) response.
                # CLOSE the producing generator before raising: its finally/
                # GeneratorExit path is where a streaming LLM handler
                # cancels the GenRequest (slot freed, load credited,
                # finish_reason "disconnect") — leaving it to the GC would
                # let an abandoned request decode to completion first.
                if self.logger:
                    self.logger.error(f"stream aborted: {e!r}")
                writer.transport.abort()
                aclose = getattr(resp.stream, "aclose", None)
                if aclose is not None:
                    try:
                        await aclose()
                    except Exception:  # noqa: BLE001 — teardown must not mask the abort
                        pass
                raise ConnectionError("stream aborted") from e
            writer.write(b"0\r\n\r\n")
            await writer.drain()
            return
        body = b"" if method == "HEAD" else resp.body
        if "content-length" not in seen:
            head.append(f"Content-Length: {len(resp.body)}\r\n".encode())
        head.append(b"\r\n")
        writer.write(b"".join(head) + body)
        await writer.drain()
