"""Transport-level request object + binding.

Parity: reference pkg/gofr/http/request.go:28-121 (Param/PathParam/Bind/
HostName, JSON vs multipart by content type) and multipartFileBind.go:11-150
(reflection file->struct binding; here: dataclass field binding with
``file`` metadata, zip support via gofr_tpu.fileutil.Zip).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, get_origin, get_type_hints
from urllib.parse import parse_qs, unquote, urlsplit

from .errors import ErrorInvalidParam

_UNPARSED = object()  # json() cache sentinel (body may legitimately be null)


class UploadedFile:
    """One part of a multipart upload (analogue of *multipart.FileHeader)."""

    __slots__ = ("filename", "content", "content_type", "headers")

    def __init__(self, filename: str, content: bytes, content_type: str = "", headers: dict | None = None):
        self.filename = filename
        self.content = content
        self.content_type = content_type
        self.headers = headers or {}

    def __len__(self) -> int:
        return len(self.content)


def _parse_multipart(body: bytes, content_type: str) -> tuple[dict[str, str], dict[str, UploadedFile]]:
    """Minimal RFC 7578 multipart/form-data parser."""
    boundary = None
    for piece in content_type.split(";"):
        piece = piece.strip()
        if piece.startswith("boundary="):
            boundary = piece[len("boundary=") :].strip('"')
    if not boundary:
        raise ErrorInvalidParam("multipart boundary")
    delim = b"--" + boundary.encode()
    fields: dict[str, str] = {}
    files: dict[str, UploadedFile] = {}
    for raw_part in body.split(delim):
        part = raw_part.strip(b"\r\n")
        if not part or part == b"--":
            continue
        if b"\r\n\r\n" in part:
            head, _, content = part.partition(b"\r\n\r\n")
        else:
            head, content = part, b""
        headers: dict[str, str] = {}
        for line in head.decode("utf-8", "replace").split("\r\n"):
            if ":" in line:
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
        disp = headers.get("content-disposition", "")
        name, filename = None, None
        for attr in disp.split(";"):
            attr = attr.strip()
            if attr.startswith("name="):
                name = attr[5:].strip('"')
            elif attr.startswith("filename="):
                filename = attr[9:].strip('"')
        if name is None:
            continue
        if filename is not None:
            files[name] = UploadedFile(filename, content, headers.get("content-type", ""), headers)
        else:
            fields[name] = content.decode("utf-8", "replace")
    return fields, files


class Request:
    """Incoming HTTP request facade handed to handlers via Context."""

    __slots__ = (
        "method", "target", "path", "query", "headers", "body",
        "path_params", "remote_addr", "route_template", "context", "_json_cache",
    )

    def __init__(
        self,
        method: str,
        target: str,
        headers: dict[str, str],
        body: bytes = b"",
        remote_addr: str = "",
    ):
        self.method = method
        self.target = target
        split = urlsplit(target)
        self.path = unquote(split.path) or "/"
        self.query: dict[str, list[str]] = parse_qs(split.query, keep_blank_values=True)
        self.headers = headers  # keys lower-cased by the server
        self.body = body
        self.path_params: dict[str, str] = {}
        self.remote_addr = remote_addr
        self.route_template = self.path
        self.context: dict[str, Any] = {}  # middleware-populated (auth claims, span)
        self._json_cache: Any = _UNPARSED

    # -- parity surface (request.go) --
    def param(self, key: str) -> str:
        """First query-string value, '' when absent (request.go Param)."""
        vals = self.query.get(key)
        return vals[0] if vals else ""

    def params(self, key: str) -> list[str]:
        return self.query.get(key, [])

    def path_param(self, key: str) -> str:
        return self.path_params.get(key, "")

    def header(self, key: str) -> str:
        return self.headers.get(key.lower(), "")

    def host_name(self) -> str:
        host = self.headers.get("host", "")
        proto = self.headers.get("x-forwarded-proto", "http")
        return f"{proto}://{host}" if host else ""

    @property
    def content_type(self) -> str:
        return self.headers.get("content-type", "")

    def json(self) -> Any:
        if self._json_cache is _UNPARSED:
            if not self.body:
                raise ErrorInvalidParam("body")
            try:
                self._json_cache = json.loads(self.body)
            except (ValueError, UnicodeDecodeError) as e:
                raise ErrorInvalidParam("body") from e
        return self._json_cache

    def bind(self, target: Any = None) -> Any:
        """Deserialize the body by content type (request.go:57-74).

        - no target: returns parsed JSON (dict/list) or multipart field dict
        - dataclass type: instantiates it from JSON keys or multipart parts;
          fields typed ``UploadedFile``/``Zip`` bind uploaded files
          (multipartFileBind.go analogue).
        """
        ct = self.content_type.split(";")[0].strip().lower()
        if ct == "multipart/form-data":
            fields, files = _parse_multipart(self.body, self.content_type)
            if target is None:
                return {**fields, **files}
            return _bind_dataclass(target, fields, files)
        data = self.json()
        if target is None:
            return data
        if dataclasses.is_dataclass(target):
            if not isinstance(data, dict):
                raise ErrorInvalidParam("body")
            return _bind_dataclass(target, data, {})
        if isinstance(target, dict) and isinstance(data, dict):
            target.update(data)
            return target
        raise ErrorInvalidParam("bind target")


def _bind_dataclass(cls: Any, fields: dict[str, Any], files: dict[str, UploadedFile]) -> Any:
    from ..fileutil import Zip  # local import: fileutil imports nothing from http

    if not dataclasses.is_dataclass(cls):
        raise ErrorInvalidParam("bind target")
    hints = get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        # `file` metadata overrides the part name (reference tag file:"name")
        part_name = f.metadata.get("file", f.name) if f.metadata else f.name
        ftype = hints.get(f.name, str)
        if ftype is UploadedFile:
            if part_name in files:
                kwargs[f.name] = files[part_name]
        elif ftype is Zip:
            if part_name in files:
                kwargs[f.name] = Zip.from_bytes(files[part_name].content)
        elif part_name in fields:
            kwargs[f.name] = _coerce(fields[part_name], ftype)
    try:
        return cls(**kwargs)
    except TypeError as e:
        missing = [f.name for f in dataclasses.fields(cls) if f.name not in kwargs
                   and f.default is dataclasses.MISSING and f.default_factory is dataclasses.MISSING]
        raise ErrorInvalidParam(*missing) from e


def _coerce(value: Any, ftype: Any) -> Any:
    if ftype in (str, Any) or get_origin(ftype) is not None:
        return value
    try:
        if ftype is bool and isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        if ftype in (int, float) and not isinstance(value, ftype):
            return ftype(value)
    except (TypeError, ValueError) as e:
        raise ErrorInvalidParam(str(value)) from e
    return value
