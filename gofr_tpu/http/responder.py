"""Response serialization: envelope, status mapping, raw/file/stream types.

Parity: reference pkg/gofr/http/responder.go:23-84 — success envelope
{"data": ...}, error envelope {"error": {"message": ...}}, Raw/File
passthrough types, method-based success codes (POST->201, DELETE->204),
status from error via the status_code seam.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, AsyncIterator

from .errors import status_from_error


class Response:
    """Wire-level response: status, headers, body bytes or async chunk iterator."""

    __slots__ = ("status", "headers", "body", "stream")

    def __init__(
        self,
        status: int = 200,
        headers: list[tuple[str, str]] | None = None,
        body: bytes = b"",
        stream: AsyncIterator[bytes] | None = None,
    ):
        self.status = status
        self.headers = headers or []
        self.body = body
        self.stream = stream


class Raw:
    """Bare JSON payload without the {"data": ...} envelope (response.Raw)."""

    __slots__ = ("data",)

    def __init__(self, data: Any):
        self.data = data


class FileResponse:
    """Bytes with a content type (response.File)."""

    __slots__ = ("content", "content_type")

    def __init__(self, content: bytes, content_type: str = "application/octet-stream"):
        self.content = content
        self.content_type = content_type


class Redirect:
    __slots__ = ("url", "status")

    def __init__(self, url: str, status: int = 302):
        self.url = url
        self.status = status


class StreamingResponse:
    """Server-sent chunked body: async iterator of byte chunks. Used for
    token-streaming LLM endpoints (no reference analogue; the TPU build's
    server-streaming requirement, BASELINE.json config 3)."""

    __slots__ = ("chunks", "content_type")

    def __init__(self, chunks: AsyncIterator[bytes], content_type: str = "text/event-stream"):
        self.chunks = chunks
        self.content_type = content_type


def _default_json(o: Any) -> Any:
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return dataclasses.asdict(o)
    if hasattr(o, "tolist"):  # numpy / jax arrays returned straight from models
        return o.tolist()
    if hasattr(o, "item") and getattr(o, "ndim", None) == 0:
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


def to_json_bytes(payload: Any) -> bytes:
    return json.dumps(payload, default=_default_json, separators=(",", ":")).encode("utf-8")


_METHOD_SUCCESS = {"POST": 201, "DELETE": 204}


def respond(result: Any, err: BaseException | None, method: str = "GET") -> Response:
    """Map a handler's (result, error) to a wire Response (responder.go:23-84)."""
    if err is not None:
        status = status_from_error(err)
        msg = getattr(err, "message", None) or str(err) or err.__class__.__name__
        body = to_json_bytes({"error": {"message": msg}})
        headers = [("Content-Type", "application/json")]
        # Overload/drain responses tell the client WHEN to come back:
        # any error carrying a finite retry_after (EngineOverloaded,
        # EngineDraining, ErrorTooManyRequests, ErrorServiceUnavailable)
        # gets the RFC 9110 Retry-After header — integer seconds, ceiled
        # so the client never retries early (docs/advanced-guide/overload.md).
        retry_after = getattr(err, "retry_after", None)
        if (
            isinstance(retry_after, (int, float))
            and retry_after == retry_after  # not NaN
            and 0 < retry_after < float("inf")
            and status in (429, 503)
        ):
            headers.append(("Retry-After", str(max(1, math.ceil(retry_after)))))
        return Response(status, headers, body)

    if isinstance(result, Response):
        return result
    if isinstance(result, Redirect):
        return Response(result.status, [("Location", result.url)], b"")
    if isinstance(result, FileResponse):
        return Response(200, [("Content-Type", result.content_type)], result.content)
    if isinstance(result, StreamingResponse):
        return Response(200, [("Content-Type", result.content_type)], b"", stream=result.chunks)
    if isinstance(result, Raw):
        return Response(200, [("Content-Type", "application/json")], to_json_bytes(result.data))

    status = _METHOD_SUCCESS.get(method, 200)
    if status == 204 and result is None:
        return Response(204, [], b"")
    body = to_json_bytes({"data": result})
    return Response(status, [("Content-Type", "application/json")], body)
