"""HTTP error taxonomy with status codes.

Parity: reference pkg/gofr/http/errors.go:11-60 — error types implementing
StatusCode(); the responder maps them to HTTP statuses. Any exception with a
``status_code`` attribute participates (the statusCodeResponder seam,
responder.go:52-74).
"""

from __future__ import annotations


class HTTPError(Exception):
    status_code = 500

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message or self.__class__.__name__


class ErrorEntityNotFound(HTTPError):
    """404. Parity: errors.go ErrorEntityNotFound."""

    status_code = 404

    def __init__(self, name: str = "", value: str = ""):
        self.name, self.value = name, value
        msg = f"No entity found with {name}: {value}" if name else "entity not found"
        super().__init__(msg)


class ErrorInvalidParam(HTTPError):
    """400. Parity: errors.go ErrorInvalidParam."""

    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        super().__init__(f"'{len(self.params)}' invalid parameter(s): {', '.join(self.params)}")


class ErrorMissingParam(HTTPError):
    """400. Parity: errors.go ErrorMissingParam."""

    status_code = 400

    def __init__(self, *params: str):
        self.params = list(params)
        super().__init__(f"'{len(self.params)}' missing parameter(s): {', '.join(self.params)}")


class ErrorInvalidRoute(HTTPError):
    """404. Parity: errors.go ErrorInvalidRoute."""

    status_code = 404

    def __init__(self):
        super().__init__("route not registered")


class ErrorRequestTimeout(HTTPError):
    """408 — request exceeded REQUEST_TIMEOUT (reference handler.go:65-71)."""

    status_code = 408

    def __init__(self):
        super().__init__("request timed out")


class ErrorPanicRecovery(HTTPError):
    """500 — unhandled exception in user handler (middleware/logger.go:127-152)."""

    status_code = 500

    def __init__(self):
        super().__init__("some unexpected error has occurred")


class ErrorServiceUnavailable(HTTPError):
    """503 — dependency down / circuit open / batch queue full /
    draining. ``retry_after`` (seconds) rides the response as the
    Retry-After header when set (the responder seam)."""

    status_code = 503
    retry_after: float | None = None

    def __init__(self, message: str = "service unavailable",
                 retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = retry_after


class ErrorTooManyRequests(HTTPError):
    """429 — the overload-control shed response (predicted-wait shed,
    queue cap, fleet admission cap; docs/advanced-guide/overload.md).
    ``retry_after`` (seconds) becomes the Retry-After header so the
    client is told WHEN capacity is predicted back instead of being
    invited to retry blind. The LLM engine's EngineOverloaded maps
    through the status_code/retry_after seams without this type; it
    exists for handlers shedding their own non-LLM work."""

    status_code = 429
    retry_after: float | None = None

    def __init__(self, message: str = "too many requests",
                 retry_after: float | None = None):
        super().__init__(message)
        if retry_after is not None:
            self.retry_after = retry_after


def status_from_error(err: BaseException) -> int:
    """The statusCodeResponder seam: any error carrying status_code."""
    code = getattr(err, "status_code", None)
    if isinstance(code, int) and 100 <= code <= 599:
        return code
    return 500
