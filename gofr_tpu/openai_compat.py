"""OpenAI-compatible serving edge: /v1/chat/completions + /v1/embeddings.

The serving stack's native surface is its own (GenRequest over
HTTP/gRPC with X-GoFr-* headers). The rest of the world speaks the
OpenAI wire format — client SDKs, eval harnesses, load tools, gateway
routers. This module maps that dialect onto the registry/handle surface
so an UNMODIFIED OpenAI client works against any registered model,
directly or through the front-router tier (the router proxies /v1/* like
any other route):

- ``POST /v1/chat/completions`` — messages -> chat-templated prompt ->
  engine stream; ``stream: true`` answers Server-Sent Events
  (``data: {chunk}\\n\\n`` ... ``data: [DONE]\\n\\n``); ``response_format
  {"type": "json_schema"}`` compiles the attached schema to a token
  grammar (gofr_tpu.structured) so the answer is schema-valid BY
  CONSTRUCTION, not by retry; ``{"type": "regex", "regex": "..."}``
  rides the same byte-regex -> token-DFA compiler for free-form
  pattern-constrained output.
- ``POST /v1/embeddings`` — mean-pooled model embedding rows,
  L2-normalized; accepts a string, a list of strings, or token-id lists.
- ``GET /v1/models`` — the registered model list, plus every resident
  LoRA adapter as a first-class model id (``parent`` names its base —
  the shape OpenAI uses for fine-tunes). ``model=<adapter>`` on the
  chat route selects that tenant's delta over the shared base program
  (docs/advanced-guide/multi-tenancy.md); an unknown non-empty name
  answers the OpenAI 404 envelope rather than silently serving base
  weights.

Identity mapping: the OpenAI ``user`` field and the native
``X-GoFr-Client``/``X-GoFr-Priority``/``X-GoFr-Session``/
``X-GoFr-Adapter`` headers both feed the fair-queuing/overload/
multi-tenancy machinery (handler.llm_request_kwargs); 429/503
responses carry Retry-After exactly like the native edge.

Tokenization: pass a tokenizer (models.tokenizer.Tokenizer or anything
with encode/decode/eos_id); without one the edge falls back to the
dependency-free byte-level tokenizer when the model's vocab admits it
(vocab_size >= 258), else text routes 400. Errors answer the OpenAI
error envelope ``{"error": {"message", "type", "code"}}``.

Knobs (docs/references/configs.md): ``GOFR_OPENAI_MODEL`` (served model
name when the request omits one), ``GOFR_OPENAI_MAX_TOKENS``
(default + cap for max_tokens), ``GOFR_OPENAI_STREAM_TIMEOUT_S``.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any

import numpy as np

from .http.responder import Response, StreamingResponse, to_json_bytes

__all__ = ["register_openai_routes", "chat_prompt"]


def _openai_error(status: int, message: str, *, etype: str = "invalid_request_error",
                  code: str | None = None, retry_after: float | None = None):
    """An HTTPError whose body respond() serializes is GoFr's envelope;
    OpenAI clients want their own — so the edge raises THIS, a Response
    carrier the handlers return directly."""
    headers = [("Content-Type", "application/json")]
    if retry_after is not None and retry_after > 0:
        import math

        headers.append(("Retry-After", str(max(1, math.ceil(retry_after)))))
    return Response(status, headers, to_json_bytes({
        "error": {"message": message, "type": etype, "code": code},
    }))


def chat_prompt(messages: list[dict]) -> str:
    """Minimal chat template: role-tagged turns plus the assistant
    cue. Checkpoint-specific templates (Gemma/Llama control tokens)
    belong to the operator's tokenizer assets; this neutral form keeps
    the wire contract model-independent."""
    parts = []
    for m in messages:
        role = str(m.get("role", "user"))
        content = m.get("content", "")
        if isinstance(content, list):  # OpenAI content-part arrays
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict)
            )
        parts.append(f"<|{role}|>\n{content}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


def register_openai_routes(
    app,
    model: str = "",
    *,
    tokenizer: Any = None,
    served_name: str | None = None,
) -> None:
    """Register the OpenAI-compatible routes on a gofr_tpu App serving
    the registered LLM ``model`` (default: GOFR_OPENAI_MODEL, else the
    single registered model)."""
    cfg = app.config
    default_model = model or cfg.get_or_default("GOFR_OPENAI_MODEL", "")
    max_tokens_cap = int(cfg.get_or_default("GOFR_OPENAI_MAX_TOKENS", "512"))
    stream_timeout = float(
        cfg.get_or_default("GOFR_OPENAI_STREAM_TIMEOUT_S", "120")
    )
    # GOFR_OPENAI_USAGE_EXTRA=1: the usage object additionally carries
    # the request's chip-time attribution (gofr_tpu.goodput) — total
    # device milliseconds and the waste breakdown. Off by default so the
    # wire format stays byte-compatible with the OpenAI schema.
    usage_extra = cfg.get_or_default(
        "GOFR_OPENAI_USAGE_EXTRA", "0"
    ) not in ("", "0")
    # per-MODEL caches: the routes dispatch on the request's model field
    # across every registered LLM, and vocabularies differ per model — a
    # shared cache would compile grammars over the wrong vocab. An
    # explicit `tokenizer=` applies to every model (single-model apps).
    state: dict[str, Any] = {"tok": {}, "embed": None, "vocab": {}}

    def _adapter_names(handle) -> list[str]:
        """Resident LoRA adapter names on this model (multi-tenancy.md),
        plus the fleet's registered set — a replica mid-rebuild may lag
        the registry, and the edge should still route to the fleet."""
        eng = getattr(handle, "engine", handle)
        names: set[str] = set()
        try:
            snap = eng.adapters()
            names.update(snap.get("resident", {}))
            names.update(snap.get("registered", ()))
        except Exception:  # noqa: BLE001 — non-engine handles have no pool
            pass
        return sorted(names)

    def _handle(ctx, name: str = ""):
        """Resolve the request's ``model`` field to (served name, handle,
        adapter). A LoRA adapter name is a first-class model id here:
        ``model=<adapter>`` routes to its base handle with the adapter
        selected (one resident base, N tenant deltas — multi-tenancy.md).
        Unknown NON-EMPTY names raise KeyError (the routes answer the
        OpenAI 404 envelope) instead of silently serving base weights to
        a tenant that asked for its fine-tune."""
        rt = ctx.container.tpu()
        llms = getattr(rt, "_llms", {})
        want = name or default_model
        if want and want in llms:
            return want, llms[want], ""
        if want:
            for base_name, handle in llms.items():
                if want in _adapter_names(handle):
                    return base_name, handle, want
            raise KeyError(
                f"model {want!r} not found; registered: "
                f"{sorted(llms) or 'none'}"
            )
        if llms:
            first = next(iter(llms))
            return first, llms[first], ""
        raise KeyError("no LLM registered")

    def _tokenizer(name: str, handle):
        if tokenizer is not None:
            return tokenizer
        cached = state["tok"].get(name)
        if cached is not None:
            return cached
        vocab = handle.engine.cfg.vocab_size if hasattr(handle, "engine") else (
            handle.cfg.vocab_size
        )
        if vocab >= 258:
            from .models.tokenizer import ByteTokenizer

            state["tok"][name] = ByteTokenizer(vocab)
            return state["tok"][name]
        return None

    def _grammar(name: str, tok, response_format):
        if not response_format:
            return None
        ftype = response_format.get("type")
        if ftype in (None, "text"):
            return None
        if ftype not in ("json_schema", "regex"):
            raise _OpenAIReject(_openai_error(
                400,
                f"response_format type {ftype!r} unsupported; use "
                "'json_schema' or 'regex' (a full free-form 'json_object' "
                "grammar needs a pushdown automaton, not a DFA)",
            ))
        if ftype == "regex":
            pattern = response_format.get("regex") or response_format.get(
                "pattern"
            )
            if not isinstance(pattern, str) or not pattern:
                raise _OpenAIReject(_openai_error(
                    400, "response_format.regex missing (pattern string)",
                ))
            schema = None
        else:
            spec = response_format.get("json_schema") or {}
            schema = spec.get("schema", spec if "properties" in spec else None)
            if schema is None:
                raise _OpenAIReject(_openai_error(
                    400, "response_format.json_schema.schema missing",
                ))
        if tok is None:
            raise _OpenAIReject(_openai_error(
                400, f"{ftype} needs a tokenizer on this deployment",
            ))
        from .structured import (
            JsonSchemaError,
            grammar_cache,
            vocab_from_tokenizer,
        )

        if name not in state["vocab"]:
            state["vocab"][name] = vocab_from_tokenizer(tok)
        eos = getattr(tok, "eos_id", None)
        if eos is None:
            raise _OpenAIReject(_openai_error(
                400, "tokenizer exposes no eos; cannot close a grammar",
            ))
        try:
            if ftype == "regex":
                return grammar_cache.get_regex(
                    pattern, state["vocab"][name], int(eos)
                )
            return grammar_cache.get(schema, state["vocab"][name], int(eos))
        except JsonSchemaError as e:
            raise _OpenAIReject(_openai_error(400, str(e))) from e

    class _OpenAIReject(Exception):
        def __init__(self, resp: Response):
            self.resp = resp

    def _gen_kwargs(ctx, body, adapter: str = "") -> dict:
        from .handler import llm_request_kwargs

        kw = llm_request_kwargs(ctx)
        user = body.get("user")
        if user and not kw.get("client"):
            kw["client"] = str(user)
        # adapter from model-name resolution; an explicit X-GoFr-Adapter
        # header (already in kw) wins — it is the more specific signal
        if adapter and not kw.get("adapter"):
            kw["adapter"] = adapter
        return kw

    def _submit(ctx, name, handle, body, tok, adapter: str = ""):
        from .llm import (
            EngineDraining,
            EngineOverloaded,
            GenRequest,
            UnknownAdapterError,
        )

        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise _OpenAIReject(_openai_error(400, "messages must be a non-empty list"))
        if body.get("n", 1) not in (1, None):
            raise _OpenAIReject(_openai_error(400, "n > 1 unsupported"))
        grammar = _grammar(name, tok, body.get("response_format"))
        if tok is None:
            raise _OpenAIReject(_openai_error(
                400,
                "no tokenizer on this deployment and the model vocabulary "
                "is too small for the byte fallback; send token ids via "
                "the native /generate surface",
            ))
        prompt = chat_prompt(messages)
        toks = tok.encode(prompt)
        max_new = int(
            body.get("max_completion_tokens")
            or body.get("max_tokens")
            or min(64, max_tokens_cap)
        )
        max_new = max(1, min(max_new, max_tokens_cap))
        eos = grammar.eos_id if grammar is not None else (
            tok.eos_id if tok.eos_id is not None else -1
        )
        req = GenRequest(
            toks,
            max_new_tokens=max_new,
            temperature=float(body.get("temperature") or 0.0),
            eos_token=eos,
            grammar=grammar,
            **_gen_kwargs(ctx, body, adapter),
        )
        try:
            return handle.submit(req), len(toks)
        except (EngineOverloaded, EngineDraining) as e:
            status = 429 if isinstance(e, EngineOverloaded) else 503
            raise _OpenAIReject(_openai_error(
                status, str(e), etype="rate_limit_error" if status == 429
                else "service_unavailable",
                retry_after=getattr(e, "retry_after", None),
            )) from e
        except UnknownAdapterError as e:
            raise _OpenAIReject(_openai_error(
                404, str(e), etype="not_found_error",
            )) from e
        except ValueError as e:
            raise _OpenAIReject(_openai_error(400, str(e))) from e

    def _finish(reason: str | None) -> str:
        return "stop" if reason == "eos" else (
            "length" if reason in ("length", None) else str(reason)
        )

    async def chat_completions(ctx):
        body = ctx.bind()
        if not isinstance(body, dict):
            return _openai_error(400, "body must be a JSON object")
        try:
            name, handle, adapter = _handle(ctx, str(body.get("model") or ""))
        except KeyError as e:
            return _openai_error(404, str(e), etype="not_found_error")
        tok = _tokenizer(name, handle)
        try:
            req, n_prompt = _submit(ctx, name, handle, body, tok, adapter)
        except _OpenAIReject as e:
            return e.resp
        cid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = int(time.time())
        # answers echo the model the CLIENT selected: the adapter name
        # when the request routed through a tenant fine-tune
        base = {"id": cid, "created": created, "model": adapter or name}
        eos_id = req.eos_token

        if body.get("stream"):
            async def sse():
                emitted: list[int] = []
                prev = ""
                head = {
                    **base,
                    "object": "chat.completion.chunk",
                    "choices": [{
                        "index": 0,
                        "delta": {"role": "assistant", "content": ""},
                        "finish_reason": None,
                    }],
                }
                yield b"data: " + to_json_bytes(head) + b"\n\n"
                # a vanished client GeneratorExits the async-for; astream's
                # disconnect hook cancels the engine request and the exit
                # propagates — only a normally-finished stream reaches the
                # terminal chunk below
                async for t in req.astream(timeout=stream_timeout):
                    if t == eos_id:
                        continue
                    emitted.append(t)
                    text = tok.decode(emitted)
                    delta, prev = text[len(prev):], text
                    if not delta:
                        continue  # partial multi-byte sequence
                    chunk = {
                        **base,
                        "object": "chat.completion.chunk",
                        "choices": [{
                            "index": 0,
                            "delta": {"content": delta},
                            "finish_reason": None,
                        }],
                    }
                    yield b"data: " + to_json_bytes(chunk) + b"\n\n"
                tail = {
                    **base,
                    "object": "chat.completion.chunk",
                    "choices": [{
                        "index": 0,
                        "delta": {},
                        "finish_reason": _finish(req.finish_reason),
                    }],
                }
                yield b"data: " + to_json_bytes(tail) + b"\n\n"
                yield b"data: [DONE]\n\n"

            return StreamingResponse(sse(), content_type="text/event-stream")

        import asyncio

        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            None, lambda: req.tokens(timeout=stream_timeout)
        )
        content_ids = [t for t in out if t != eos_id]
        usage = {
            "prompt_tokens": n_prompt,
            "completion_tokens": len(out),
            "total_tokens": n_prompt + len(out),
        }
        if usage_extra:
            chip = dict(getattr(req, "_chip", None) or {})
            usage["chip_time_ms"] = round(sum(chip.values()) * 1e3, 3)
            usage["chip_breakdown_ms"] = {
                c: round(v * 1e3, 3) for c, v in chip.items()
            }
        payload = {
            **base,
            "object": "chat.completion",
            "choices": [{
                "index": 0,
                "message": {
                    "role": "assistant",
                    "content": tok.decode(content_ids),
                },
                "finish_reason": _finish(req.finish_reason),
            }],
            "usage": usage,
        }
        return Response(
            200, [("Content-Type", "application/json")], to_json_bytes(payload)
        )

    def embeddings(ctx):
        body = ctx.bind()
        if not isinstance(body, dict):
            return _openai_error(400, "body must be a JSON object")
        try:
            name, handle, _adapter = _handle(ctx, str(body.get("model") or ""))
        except KeyError as e:
            return _openai_error(404, str(e), etype="not_found_error")
        raw = body.get("input")
        if raw is None:
            return _openai_error(400, "input missing")
        items = raw if isinstance(raw, list) else [raw]
        if items and isinstance(items[0], int):
            items = [items]  # single token-id list
        tok = _tokenizer(name, handle)
        if state["embed"] is None or state["embed"][0] != name:
            # the model's own embedding matrix, fetched ONCE to host:
            # mean-pooled input embeddings are the classic cheap text
            # representation and reuse the served weights verbatim
            eng = getattr(handle, "engine", handle)
            emb = np.asarray(eng.params["embed"], dtype=np.float32)
            state["embed"] = (name, emb)
        emb = state["embed"][1]
        data = []
        total_toks = 0
        for i, item in enumerate(items):
            if isinstance(item, str):
                if tok is None:
                    return _openai_error(
                        400, "text input needs a tokenizer on this deployment"
                    )
                ids = tok.encode(item, add_bos=False) if hasattr(
                    tok, "encode"
                ) else []
            elif isinstance(item, list) and all(
                isinstance(t, int) for t in item
            ):
                ids = item
            else:
                return _openai_error(400, f"input[{i}] must be text or token ids")
            ids = [t for t in ids if 0 <= t < emb.shape[0]] or [0]
            total_toks += len(ids)
            v = emb[ids].mean(axis=0)
            norm = float(np.linalg.norm(v))
            if norm > 1e-12:
                v = v / norm
            data.append({
                "object": "embedding",
                "index": i,
                "embedding": [float(x) for x in v],
            })
        return Response(200, [("Content-Type", "application/json")], to_json_bytes({
            "object": "list",
            "data": data,
            "model": name,
            "usage": {"prompt_tokens": total_toks, "total_tokens": total_toks},
        }))

    def list_models(ctx):
        rt = ctx.container.tpu()
        llms = getattr(rt, "_llms", {})
        data = [
            {
                "id": served_name or name,
                "object": "model",
                "created": 0,
                "owned_by": "gofr_tpu",
            }
            for name in llms
        ]
        # LoRA adapters are first-class model ids (multi-tenancy.md):
        # every resident adapter lists beside its base, the same shape
        # OpenAI uses for fine-tunes — `parent` names the base model
        for name, handle in llms.items():
            for aname in _adapter_names(handle):
                data.append({
                    "id": aname,
                    "object": "model",
                    "created": 0,
                    "owned_by": "gofr_tpu",
                    "parent": served_name or name,
                })
        return Response(200, [("Content-Type", "application/json")], to_json_bytes({
            "object": "list",
            "data": data,
        }))

    # chat completions get their own timeout budget: a non-streaming
    # generation legitimately runs past the API-SLO REQUEST_TIMEOUT (the
    # profile/rollout route precedent), and a streaming handler only
    # needs it to OBTAIN the generator
    app._add(
        "POST", "/v1/chat/completions", chat_completions,
        timeout_s=max(stream_timeout, app.request_timeout),
    )
    app.post("/v1/embeddings", embeddings)
    app.get("/v1/models", list_models)
