"""Structured (grammar-constrained) decoding: JSON schema -> token DFA.

A constrained generation must be valid under its grammar BY CONSTRUCTION:
instead of sampling freely and validating after the fact (reject/retry
burns decode throughput and still fails at max_new_tokens), the grammar
is compiled ON THE HOST into a token-level DFA and shipped to the device
as a dense transition table. Every sampling site in the engine's fused
programs (decode chunks, unified steps, speculative verify) then masks
the logits of a constrained slot to the tokens its current DFA state
admits and advances the state with the token actually sampled — so
constrained and unconstrained requests mix in ONE device program, and
the output parses under the schema no matter what the weights say
(docs/advanced-guide/structured-decoding.md).

The pipeline, all host-side and model-free:

1. **schema -> byte regex** (`_schema_ast`): a supported JSON-schema
   subset (object/array/string/number/integer/boolean/null/enum/const/
   anyOf, bounded repetition, fixed required-property order) lowers to a
   small regex AST over BYTES. Optional JSON whitespace is admitted at
   the structural positions. A second front-end (`_RegexParser` /
   `compile_regex`, the OpenAI edge's ``response_format={"type":
   "regex"}``) lowers a DFA-safe regex pattern STRING to the same AST —
   both ride one NFA/DFA/token-table pipeline.
2. **regex -> DFA** (`_RegexCompiler`): Thompson NFA -> subset
   construction -> prune states that cannot reach an accepting state.
3. **byte DFA -> token DFA** (`compile_token_table`): for each DFA state
   and vocabulary token, walk the token's bytes; the result is a dense
   ``int32 [n_states, vocab]`` table where entry ``< 0`` means "token not
   admitted here". Accepting byte-states admit the EOS token into a
   terminal DONE state, so a completed value can only end the stream.
   A final fixpoint prunes token-states from which no token path reaches
   DONE (the vocabulary may be unable to realize a byte path), so every
   live state always admits at least one token — the device mask can
   never go empty.

The engine guarantees (tests/test_structured.py): greedy constrained
output parses and validates across every KV layout; constrained spec-on
is token-identical to constrained spec-off; acceptance on constrained
text meets or beats the unconstrained baseline (the drafter's proposals
are pre-filtered by the same DFA, `TokenGrammar.filter_draft`).

Knobs: ``TPU_LLM_CONSTRAINED`` (engine support, on by default),
``TPU_LLM_CONSTRAINED_MAX_STATES`` (compile-time state bound),
``TPU_LLM_CONSTRAINED_GRAMMARS`` (resident grammar table slots).
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable

import numpy as np

__all__ = [
    "JsonSchemaError",
    "RegexError",
    "TokenGrammar",
    "compile_json_schema",
    "compile_regex",
    "vocab_from_tokenizer",
    "grammar_cache",
]

DONE = -2  # token-table terminal marker (EOS consumed; nothing follows)
_WS = b" \t\n\r"


class JsonSchemaError(ValueError):
    """Unsupported/malformed schema, or a vocabulary that cannot realize
    it. Carries status_code so the serving edges surface it as a 400 —
    a client bug, never a server error."""

    status_code = 400


class RegexError(JsonSchemaError):
    """Malformed/unsupported regex pattern. Subclasses JsonSchemaError so
    every existing edge catch (grammar compile -> 400) covers the regex
    front-end too."""


# ---------------------------------------------------------------------------
# regex AST over bytes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Lit:
    data: bytes


@dataclass(frozen=True)
class _Class:
    allowed: frozenset  # of int bytes


@dataclass(frozen=True)
class _Seq:
    parts: tuple


@dataclass(frozen=True)
class _Alt:
    options: tuple


@dataclass(frozen=True)
class _Rep:
    node: Any
    lo: int
    hi: int | None  # None = unbounded


_EPS = _Seq(())


def _seq(*parts) -> Any:
    flat = [p for p in parts if not (isinstance(p, _Seq) and not p.parts)]
    return flat[0] if len(flat) == 1 else _Seq(tuple(flat))


def _alt(*options) -> Any:
    return options[0] if len(options) == 1 else _Alt(tuple(options))


def _cls(byte_values: Iterable[int]) -> _Class:
    return _Class(frozenset(byte_values))


# ---------------------------------------------------------------------------
# schema -> regex AST
# ---------------------------------------------------------------------------

# printable ASCII string content, minus the quote and backslash that end
# or escape it. Multi-byte UTF-8 is deliberately not generated: the
# grammar guarantees the OUTPUT is valid JSON text, and ASCII keeps the
# byte DFA small and the guarantee tokenizer-independent.
_STR_CHARS = frozenset(range(0x20, 0x7F)) - {0x22, 0x5C}
_DIGITS = frozenset(range(0x30, 0x3A))
_DIGITS19 = frozenset(range(0x31, 0x3A))
_MAX_DEPTH = 12


_WS_MAX = 2  # longest admitted whitespace run at a structural position


def _ws(opt: bool) -> Any:
    # BOUNDED optional whitespace: an unbounded ws* self-loop hands a
    # greedy model an attractor (space is a high-probability token) it
    # can spin in until max_new_tokens — the bound forces a structural
    # token after at most _WS_MAX blanks, so constrained decoding always
    # makes grammatical progress
    return _Rep(_cls(_WS), 0, _WS_MAX) if opt else _EPS


def _string_ast(schema: dict) -> Any:
    max_len = schema.get("maxLength")
    min_len = int(schema.get("minLength", 0) or 0)
    char = _alt(
        _cls(_STR_CHARS),
        _seq(_Lit(b"\\"), _cls(frozenset(b'"\\/bfnrt'))),
    )
    hi = int(max_len) if max_len is not None else None
    if hi is not None and hi < min_len:
        raise JsonSchemaError(
            f"maxLength {hi} < minLength {min_len}"
        )
    return _seq(_Lit(b'"'), _Rep(char, min_len, hi), _Lit(b'"'))


def _number_ast(integer: bool) -> Any:
    # bounded digit runs keep the DFA small AND bound how long a greedy
    # model can ride the digit attractor before the grammar forces a
    # close (1e9 magnitudes + 6 fraction digits + 2-digit exponents
    # cover realistic payloads; the bound is a compile artifact, not a
    # validation rule)
    int_part = _alt(
        _Lit(b"0"),
        _seq(_cls(_DIGITS19), _Rep(_cls(_DIGITS), 0, 9)),
    )
    head = _seq(_Rep(_Lit(b"-"), 0, 1), int_part)
    if integer:
        return head
    frac = _Rep(_seq(_Lit(b"."), _Rep(_cls(_DIGITS), 1, 6)), 0, 1)
    exp = _Rep(
        _seq(
            _cls(frozenset(b"eE")),
            _Rep(_cls(frozenset(b"+-")), 0, 1),
            _Rep(_cls(_DIGITS), 1, 2),
        ),
        0, 1,
    )
    return _seq(head, frac, exp)


def _json_literal(value: Any) -> _Lit:
    return _Lit(json.dumps(value, separators=(",", ":")).encode())


def _schema_ast(schema: Any, ws: bool, depth: int = 0) -> Any:
    """Lower one (sub)schema to a byte-regex AST. Raises JsonSchemaError
    on anything outside the supported subset — a silent fallback would
    emit output the caller's validator then rejects, which is exactly
    the failure mode constrained decoding exists to remove."""
    if depth > _MAX_DEPTH:
        raise JsonSchemaError(f"schema nesting exceeds {_MAX_DEPTH}")
    if not isinstance(schema, dict):
        raise JsonSchemaError(f"schema must be an object, got {type(schema).__name__}")
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise JsonSchemaError("enum must be a non-empty list")
        return _alt(*[_json_literal(v) for v in vals])
    if "const" in schema:
        return _json_literal(schema["const"])
    if "anyOf" in schema:
        opts = schema["anyOf"]
        if not isinstance(opts, list) or not opts:
            raise JsonSchemaError("anyOf must be a non-empty list")
        return _alt(*[_schema_ast(s, ws, depth + 1) for s in opts])
    t = schema.get("type")
    if isinstance(t, list):
        if not t:
            raise JsonSchemaError("empty type list")
        return _alt(*[
            _schema_ast({**schema, "type": one}, ws, depth + 1) for one in t
        ])
    if t == "string":
        return _string_ast(schema)
    if t == "integer":
        return _number_ast(integer=True)
    if t == "number":
        return _number_ast(integer=False)
    if t == "boolean":
        return _alt(_Lit(b"true"), _Lit(b"false"))
    if t == "null":
        return _Lit(b"null")
    if t == "array":
        item = _schema_ast(schema.get("items", {"type": "string"}), ws, depth + 1)
        lo = int(schema.get("minItems", 0) or 0)
        hi = schema.get("maxItems")
        hi = int(hi) if hi is not None else None
        if hi is not None and hi < lo:
            raise JsonSchemaError(f"maxItems {hi} < minItems {lo}")
        sep_item = _seq(_ws(ws), _Lit(b","), _ws(ws), item)
        if hi == 0:
            body = _EPS
        else:
            rest = _Rep(
                sep_item, max(0, lo - 1), None if hi is None else hi - 1
            )
            some = _seq(item, rest)
            body = some if lo > 0 else _Rep(some, 0, 1)
        return _seq(_Lit(b"["), _ws(ws), body, _ws(ws), _Lit(b"]"))
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise JsonSchemaError("properties must be an object")
        required = schema.get("required")
        if required is None:
            required = list(props)
        for name in required:
            if name not in props:
                raise JsonSchemaError(f"required property {name!r} not in properties")
        # fixed emission order = the properties' declared order,
        # filtered to the required set: every emitted object is valid
        # under the schema (required present, no additionals) and the
        # DFA stays linear in the property count instead of exploding
        # over orderings
        emit = [n for n in props if n in set(required)]
        parts: list[Any] = [_Lit(b"{"), _ws(ws)]
        for i, name in enumerate(emit):
            if i:
                parts += [_ws(ws), _Lit(b","), _ws(ws)]
            parts += [
                _json_literal(name), _ws(ws), _Lit(b":"), _ws(ws),
                _schema_ast(props[name], ws, depth + 1),
            ]
        parts += [_ws(ws), _Lit(b"}")]
        return _seq(*parts)
    if t is None:
        # no type, no enum/const/anyOf: any JSON *scalar* (a fully
        # recursive "any value" grammar needs a PDA, not a DFA)
        return _alt(
            _string_ast({}),
            _number_ast(integer=False),
            _Lit(b"true"), _Lit(b"false"), _Lit(b"null"),
        )
    raise JsonSchemaError(f"unsupported schema type {t!r}")


# ---------------------------------------------------------------------------
# regex pattern string -> regex AST
# ---------------------------------------------------------------------------

# `.` and negated classes range over printable ASCII: the same closed
# byte domain the schema front-end emits (_STR_CHARS rationale) — a DFA
# over "any byte" would admit output the tokenizer cannot round-trip
_ANY_CHARS = frozenset(range(0x20, 0x7F))
_REP_MAX = 4096  # {m,n} bound — a typo like {1,999999} must not explode the NFA

_ESC_CLASSES = {
    "d": _DIGITS,
    "D": _ANY_CHARS - _DIGITS,
    "w": frozenset(b"abcdefghijklmnopqrstuvwxyz"
                   b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": frozenset(b" \t\n\r\f\v"),
}
_ESC_CLASSES["W"] = _ANY_CHARS - _ESC_CLASSES["w"]
_ESC_CLASSES["S"] = _ANY_CHARS - _ESC_CLASSES["s"]
_ESC_LITERALS = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B,
                 "0": 0x00}


class _RegexParser:
    """Recursive-descent parser for the DFA-safe regex subset, lowering a
    pattern string to the SAME byte-level AST the JSON-schema front-end
    emits — so ``response_format={"type": "regex"}`` rides the existing
    NFA/DFA/token-table pipeline unchanged.

    Supported: literals, escapes (``\\d \\D \\w \\W \\s \\S \\n \\t`` +
    escaped metachars), ``.``, character classes ``[a-z]``/``[^...]``,
    grouping ``(...)`` / ``(?:...)``, alternation ``|``, quantifiers
    ``* + ? {m} {m,} {m,n}``, optional anchors ``^``/``$`` (whole-string
    match is implicit — the token DFA only ends a stream at EOS in an
    accepting state). NOT supported (would need more than a DFA, or make
    masks ambiguous): backreferences, lookaround, lazy quantifiers,
    named groups, unicode classes."""

    def __init__(self, pattern: str):
        try:
            self.data = pattern.encode("ascii")
        except UnicodeEncodeError as e:
            raise RegexError(
                "regex patterns are byte-level: non-ASCII literals are "
                "not supported"
            ) from e
        self.pos = 0

    def _peek(self) -> str:
        return chr(self.data[self.pos]) if self.pos < len(self.data) else ""

    def _next(self) -> str:
        ch = self._peek()
        self.pos += 1
        return ch

    def parse(self) -> Any:
        if self._peek() == "^":
            self.pos += 1  # whole-string match is implicit
        node = self._alternation()
        if self.pos < len(self.data):
            raise RegexError(
                f"unexpected {self._peek()!r} at position {self.pos}"
            )
        return node

    def _alternation(self) -> Any:
        opts = [self._sequence()]
        while self._peek() == "|":
            self.pos += 1
            opts.append(self._sequence())
        return _alt(*opts)

    def _sequence(self) -> Any:
        parts: list[Any] = []
        while True:
            ch = self._peek()
            if ch in ("", "|", ")"):
                break
            if ch == "$":
                # accept a trailing anchor; anywhere else it's an error
                # surfaced by parse()'s trailing-input check
                if self.pos == len(self.data) - 1:
                    self.pos += 1
                    break
                raise RegexError("'$' is only supported at the pattern end")
            parts.append(self._quantified())
        return _seq(*parts) if parts else _EPS

    def _quantified(self) -> Any:
        node = self._atom()
        ch = self._peek()
        if ch == "*":
            self.pos += 1
            node = _Rep(node, 0, None)
        elif ch == "+":
            self.pos += 1
            node = _Rep(node, 1, None)
        elif ch == "?":
            self.pos += 1
            node = _Rep(node, 0, 1)
        elif ch == "{":
            node = _Rep(node, *self._braces())
        if self._peek() in ("*", "+", "?"):
            raise RegexError(
                f"lazy/stacked quantifiers unsupported at position {self.pos}"
            )
        return node

    def _braces(self) -> tuple[int, int | None]:
        start = self.pos
        self.pos += 1  # consume '{'
        body = ""
        while self._peek() not in ("}", ""):
            body += self._next()
        if self._next() != "}":
            raise RegexError(f"unterminated {{...}} at position {start}")
        try:
            if "," not in body:
                lo = hi = int(body)
            else:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s.strip() else None
        except ValueError as e:
            raise RegexError(f"malformed repetition {{{body}}}") from e
        if lo < 0 or (hi is not None and (hi < lo or hi > _REP_MAX)) or lo > _REP_MAX:
            raise RegexError(f"repetition {{{body}}} out of range (max {_REP_MAX})")
        return lo, hi

    def _atom(self) -> Any:
        ch = self._next()
        if ch == "":
            raise RegexError("unexpected end of pattern")
        if ch == "(":
            if self._peek() == "?":
                self.pos += 1
                if self._next() != ":":
                    raise RegexError(
                        "only non-capturing (?:...) groups are supported "
                        "(no lookaround/named groups)"
                    )
            node = self._alternation()
            if self._next() != ")":
                raise RegexError("unbalanced '('")
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            return _cls(_ANY_CHARS)
        if ch == "\\":
            return self._escape(in_class=False)
        if ch in "*+?{":
            raise RegexError(f"quantifier {ch!r} with nothing to repeat")
        if ch in ")]}":
            raise RegexError(f"unbalanced {ch!r}")
        return _Lit(ch.encode())

    def _escape(self, *, in_class: bool) -> Any:
        ch = self._next()
        if ch == "":
            raise RegexError("dangling backslash")
        if ch in _ESC_CLASSES:
            allowed = _ESC_CLASSES[ch]
            return frozenset(allowed) if in_class else _cls(allowed)
        if ch in _ESC_LITERALS:
            b = _ESC_LITERALS[ch]
        elif ch == "x":
            hexs = "".join(self._next() for _ in range(2))
            try:
                b = int(hexs, 16)
            except ValueError as e:
                raise RegexError(f"malformed \\x escape \\x{hexs}") from e
        elif not ch.isalnum():
            b = ord(ch)  # escaped metachar: \. \\ \[ \+ ...
        else:
            raise RegexError(f"unsupported escape \\{ch}")
        return frozenset([b]) if in_class else _Lit(bytes([b]))

    def _char_class(self) -> _Class:
        start = self.pos
        negate = self._peek() == "^"
        if negate:
            self.pos += 1
        allowed: set[int] = set()
        first = True
        while True:
            ch = self._next()
            if ch == "":
                raise RegexError(f"unterminated [...] at position {start}")
            if ch == "]" and not first:
                break
            first = False
            if ch == "\\":
                got = self._escape(in_class=True)
                allowed |= got
                continue
            lo = ord(ch)
            if self._peek() == "-" and self.pos + 1 < len(self.data) and \
                    chr(self.data[self.pos + 1]) != "]":
                self.pos += 1  # consume '-'
                hi_ch = self._next()
                if hi_ch == "\\":
                    got = self._escape(in_class=True)
                    if len(got) != 1:
                        raise RegexError("class range endpoint must be one char")
                    hi = next(iter(got))
                else:
                    hi = ord(hi_ch)
                if hi < lo:
                    raise RegexError(
                        f"reversed class range at position {self.pos}"
                    )
                allowed |= set(range(lo, hi + 1))
            else:
                allowed.add(lo)
        if negate:
            allowed = set(_ANY_CHARS) - allowed
        if not allowed:
            raise RegexError("character class admits nothing")
        return _cls(allowed)


def compile_regex(
    pattern: str,
    vocab: list[bytes | str],
    eos_id: int,
    *,
    max_states: int | None = None,
) -> TokenGrammar:
    """Compile a regex pattern string into a TokenGrammar for one
    vocabulary — the ``response_format={"type": "regex"}`` front-end.
    The pattern is a WHOLE-string match (anchors optional): the token
    DFA admits EOS only in accepting states, so the stream can only end
    on a complete match."""
    import os

    if not isinstance(pattern, str) or not pattern:
        raise RegexError("pattern must be a non-empty string")
    if max_states is None:
        max_states = int(
            os.environ.get("TPU_LLM_CONSTRAINED_MAX_STATES", "4096") or 4096
        )
    norm = [v.encode() if isinstance(v, str) else bytes(v) for v in vocab]
    ast = _RegexParser(pattern).parse()
    dfa, accepting = _RegexCompiler().compile(ast, max_states)
    table = compile_token_table(dfa, accepting, norm, eos_id)
    key = hashlib.sha256(
        b"re|" + pattern.encode() + b"|" + _vocab_key(norm).encode()
        + b"|" + str(eos_id).encode()
    ).hexdigest()
    return TokenGrammar(
        table, eos_id=eos_id, key=key, accepting_start=0 in accepting
    )


# ---------------------------------------------------------------------------
# regex -> byte DFA (Thompson NFA + subset construction + pruning)
# ---------------------------------------------------------------------------

class _RegexCompiler:
    def __init__(self) -> None:
        self.eps: list[list[int]] = []  # state -> eps successors
        self.trans: list[dict[int, int]] = []  # state -> {byte: succ}

    def _new(self) -> int:
        self.eps.append([])
        self.trans.append({})
        return len(self.eps) - 1

    def _build(self, node: Any) -> tuple[int, int]:
        """Thompson construction: returns (entry, exit) NFA states."""
        if isinstance(node, _Lit):
            entry = cur = self._new()
            for b in node.data:
                nxt = self._new()
                self.trans[cur][b] = nxt
                cur = nxt
            return entry, cur
        if isinstance(node, _Class):
            if not node.allowed:
                raise JsonSchemaError("empty character class")
            entry, exit_ = self._new(), self._new()
            for b in node.allowed:
                # one shared exit; per-byte transitions on the entry
                self.trans[entry][b] = exit_
            return entry, exit_
        if isinstance(node, _Seq):
            entry = cur = self._new()
            for part in node.parts:
                s, e = self._build(part)
                self.eps[cur].append(s)
                cur = e
            return entry, cur
        if isinstance(node, _Alt):
            entry, exit_ = self._new(), self._new()
            for opt in node.options:
                s, e = self._build(opt)
                self.eps[entry].append(s)
                self.eps[e].append(exit_)
            return entry, exit_
        if isinstance(node, _Rep):
            entry = cur = self._new()
            for _ in range(node.lo):
                s, e = self._build(node.node)
                self.eps[cur].append(s)
                cur = e
            if node.hi is None:
                s, e = self._build(node.node)
                loop = self._new()
                self.eps[cur].append(loop)
                self.eps[loop].append(s)
                self.eps[e].append(loop)
                return entry, loop
            exit_ = self._new()
            self.eps[cur].append(exit_)
            for _ in range(node.hi - node.lo):
                s, e = self._build(node.node)
                self.eps[cur].append(s)
                cur = e
                self.eps[cur].append(exit_)
            return entry, exit_
        raise JsonSchemaError(f"unknown regex node {node!r}")

    def compile(self, node: Any, max_states: int) -> tuple[list[dict[int, int]], set[int]]:
        """Byte-level DFA: (transitions per state, accepting set). State 0
        is the start; only productive states (an accepting state is
        byte-reachable) are kept."""
        start, accept = self._build(node)

        def closure(states: frozenset) -> frozenset:
            seen = set(states)
            stack = list(states)
            while stack:
                for e in self.eps[stack.pop()]:
                    if e not in seen:
                        seen.add(e)
                        stack.append(e)
            return frozenset(seen)

        start_c = closure(frozenset([start]))
        ids: dict[frozenset, int] = {start_c: 0}
        table: list[dict[int, int]] = [{}]
        accepting: set[int] = set()
        if accept in start_c:
            accepting.add(0)
        work = [start_c]
        while work:
            cur = work.pop()
            cid = ids[cur]
            by_byte: dict[int, set[int]] = {}
            for s in cur:
                for b, nxt in self.trans[s].items():
                    by_byte.setdefault(b, set()).add(nxt)
            for b, nxts in by_byte.items():
                nc = closure(frozenset(nxts))
                if nc not in ids:
                    if len(ids) >= max_states:
                        raise JsonSchemaError(
                            f"grammar exceeds {max_states} DFA states "
                            "(raise TPU_LLM_CONSTRAINED_MAX_STATES or "
                            "simplify the schema)"
                        )
                    ids[nc] = len(ids)
                    table.append({})
                    if accept in nc:
                        accepting.add(ids[nc])
                    work.append(nc)
                table[cid][b] = ids[nc]
        # prune states that cannot reach an accepting state (subset
        # construction can mint them; a masked sampler stuck in one
        # could never finish)
        good = set(accepting)
        changed = True
        while changed:
            changed = False
            for sid, row in enumerate(table):
                if sid not in good and any(n in good for n in row.values()):
                    good.add(sid)
                    changed = True
        if 0 not in good:
            raise JsonSchemaError("grammar accepts no string")
        remap = {old: new for new, old in enumerate(sorted(good))}
        out = [
            {b: remap[n] for b, n in table[old].items() if n in good}
            for old in sorted(good)
        ]
        acc = {remap[s] for s in accepting}
        return out, acc


# ---------------------------------------------------------------------------
# byte DFA -> token DFA
# ---------------------------------------------------------------------------

class TokenGrammar:
    """A compiled token-level DFA over one model vocabulary.

    ``table[s, t]`` is the state after emitting token ``t`` in state
    ``s`` — ``-1`` if the grammar does not admit the token there, and
    ``DONE`` (= -2 exactly once, remapped to the terminal row) after the
    EOS that closes a completed value. The engine ships this table to
    the device verbatim; ``advance``/``allowed``/``filter_draft`` are
    the host mirrors the drafter and the tests drive."""

    def __init__(self, table: np.ndarray, *, eos_id: int, key: str,
                 accepting_start: bool = False):
        self.table = np.ascontiguousarray(table, dtype=np.int32)
        self.n_states, self.vocab_size = self.table.shape
        self.eos_id = int(eos_id)
        self.key = key
        self.start = 0
        self.accepting_start = accepting_start

    def advance(self, state: int, token: int) -> int:
        """Host mirror of the device state advance: next state, or a
        negative id once the path leaves the grammar (dead) or the EOS
        closed it (done)."""
        if state < 0 or state >= self.n_states:
            return -1
        if token < 0 or token >= self.vocab_size:
            return -1
        return int(self.table[state, token])

    def advance_all(self, state: int, tokens: Iterable[int]) -> int:
        for t in tokens:
            if state < 0:
                return state
            state = self.advance(state, t)
        return state

    def allowed(self, state: int) -> np.ndarray:
        """Boolean mask of tokens admitted in ``state`` (all-False once
        dead/done)."""
        if state < 0 or state >= self.n_states:
            return np.zeros((self.vocab_size,), bool)
        return self.table[state] >= 0

    def filter_draft(self, state: int, draft: list[int]) -> list[int]:
        """Longest grammar-admissible prefix of a drafted continuation —
        the speculative drafter's pre-filter: proposing a token the mask
        will reject wastes exactly one verify position, so the draft is
        cut at the first inadmissible token."""
        out: list[int] = []
        for t in draft:
            nxt = self.advance(state, t)
            if nxt < 0:
                break
            out.append(t)
            state = nxt
        return out

    def __repr__(self) -> str:  # debug/stats readability
        return (
            f"TokenGrammar(states={self.n_states}, vocab={self.vocab_size}, "
            f"eos={self.eos_id}, key={self.key[:12]})"
        )


def _walk(dfa: list[dict[int, int]], state: int, data: bytes) -> int:
    for b in data:
        nxt = dfa[state].get(b, -1)
        if nxt < 0:
            return -1
        state = nxt
    return state


def compile_token_table(
    dfa: list[dict[int, int]],
    accepting: set[int],
    vocab: list[bytes],
    eos_id: int,
) -> np.ndarray:
    """Dense token transition table from a byte DFA. The final fixpoint
    removes transitions into token-level dead ends, so every reachable
    state admits at least one token (possibly EOS) — the device-side
    mask can never be empty mid-stream."""
    n = len(dfa)
    V = len(vocab)
    if not (0 <= eos_id < V):
        raise JsonSchemaError(f"eos_id {eos_id} outside vocab of {V}")
    done = n  # terminal row, appended below
    table = np.full((n + 1, V), -1, np.int32)
    for s in range(n):
        for t, data in enumerate(vocab):
            if t == eos_id or not data:
                continue
            nxt = _walk(dfa, s, data)
            if nxt >= 0:
                table[s, t] = nxt
        if s in accepting:
            table[s, eos_id] = done
    # token-level pruning: a state whose every outgoing edge died cannot
    # make progress; cut edges into it and iterate
    live = np.ones((n + 1,), bool)
    while True:
        out_deg = (table >= 0).sum(axis=1)
        bad = (out_deg == 0) & live
        bad[done] = False
        if not bad.any():
            break
        live &= ~bad
        dead_ids = np.where(bad)[0]
        table[np.isin(table, dead_ids)] = -1
    if not live[0]:
        raise JsonSchemaError(
            "vocabulary cannot realize this grammar (no token path from "
            "the start state to a completed value)"
        )
    return table


def _vocab_key(vocab: list[bytes]) -> str:
    h = hashlib.sha256()
    for data in vocab:
        h.update(len(data).to_bytes(4, "little"))
        h.update(data)
    return h.hexdigest()[:16]


def compile_json_schema(
    schema: Any,
    vocab: list[bytes | str],
    eos_id: int,
    *,
    max_states: int | None = None,
    whitespace: bool = True,
) -> TokenGrammar:
    """Compile a JSON schema into a TokenGrammar for one vocabulary.

    ``vocab[t]`` is the byte string token ``t`` contributes to the
    output text (b"" for specials — they are never admitted). With
    ``whitespace`` the grammar admits optional blanks at JSON's
    structural positions, which is what lets a model's natural
    formatting survive constraint."""
    import os

    if max_states is None:
        max_states = int(
            os.environ.get("TPU_LLM_CONSTRAINED_MAX_STATES", "4096") or 4096
        )
    norm = [v.encode() if isinstance(v, str) else bytes(v) for v in vocab]
    ast = _schema_ast(schema, whitespace)
    dfa, accepting = _RegexCompiler().compile(ast, max_states)
    table = compile_token_table(dfa, accepting, norm, eos_id)
    key = hashlib.sha256(
        json.dumps(schema, sort_keys=True, separators=(",", ":")).encode()
        + b"|" + _vocab_key(norm).encode() + b"|" + str(eos_id).encode()
        + b"|ws" + (b"1" if whitespace else b"0")
    ).hexdigest()
    return TokenGrammar(
        table, eos_id=eos_id, key=key, accepting_start=0 in accepting
    )


# ---------------------------------------------------------------------------
# vocabulary extraction + process-level grammar cache
# ---------------------------------------------------------------------------

_BYTE_TOKEN = ("<0x", ">")


def vocab_from_tokenizer(tok: Any) -> list[bytes]:
    """Best-effort id -> byte-string vocabulary from a tokenizer.

    Accepts the repo's models.tokenizer.Tokenizer (HF `tokenizers`
    wrapper), a raw HF tokenizer, or any object exposing a ``vocab``
    list. SentencePiece/byte-BPE markers (▁, Ġ, Ċ, <0xNN>) are folded to
    their byte meaning; tokens that cannot be resolved map to b"" and
    are simply never admitted by a grammar."""
    if hasattr(tok, "vocab") and isinstance(getattr(tok, "vocab"), (list, tuple)):
        return [
            v.encode() if isinstance(v, str) else bytes(v) for v in tok.vocab
        ]
    inner = getattr(tok, "_tok", tok)
    if not hasattr(inner, "id_to_token") or not hasattr(inner, "get_vocab_size"):
        raise JsonSchemaError(
            "tokenizer exposes neither .vocab nor id_to_token(); cannot "
            "build a grammar vocabulary"
        )
    out: list[bytes] = []
    for i in range(int(inner.get_vocab_size())):
        piece = inner.id_to_token(i)
        if piece is None:
            out.append(b"")
            continue
        if piece.startswith(_BYTE_TOKEN[0]) and piece.endswith(_BYTE_TOKEN[1]):
            try:
                out.append(bytes([int(piece[3:-1], 16)]))
                continue
            except ValueError:
                pass
        piece = piece.replace("▁", " ").replace("Ġ", " ")
        piece = piece.replace("Ċ", "\n")
        if piece.startswith("<") and piece.endswith(">"):
            out.append(b"")  # special marker token
            continue
        out.append(piece.encode("utf-8", "ignore"))
    return out


class _GrammarCache:
    """Process-level LRU of compiled grammars keyed by (schema, vocab,
    eos): the serving edge compiles each distinct schema once, repeat
    requests reuse the table (compilation is milliseconds for realistic
    schemas but the edge should not pay it per request)."""

    def __init__(self, cap: int = 64):
        self.cap = cap
        self._lock = threading.Lock()
        self._items: dict[str, TokenGrammar] = {}

    def get(
        self, schema: Any, vocab: list[bytes], eos_id: int, **kw
    ) -> TokenGrammar:
        pre = hashlib.sha256(
            json.dumps(schema, sort_keys=True, separators=(",", ":")).encode()
            + b"|" + _vocab_key(vocab).encode() + b"|" + str(eos_id).encode()
            # compile options are part of the identity: a whitespace=False
            # grammar must not satisfy a default-options lookup
            + b"|" + json.dumps(kw, sort_keys=True).encode()
        ).hexdigest()
        with self._lock:
            g = self._items.pop(pre, None)
            if g is not None:
                self._items[pre] = g  # LRU bump
                return g
        g = compile_json_schema(schema, vocab, eos_id, **kw)
        return self._put(pre, g)

    def get_regex(
        self, pattern: str, vocab: list[bytes], eos_id: int, **kw
    ) -> TokenGrammar:
        """Regex twin of get(): same LRU, keyed under a 're|' prefix so a
        pattern that textually equals a schema dump cannot collide."""
        pre = hashlib.sha256(
            b"re|" + str(pattern).encode()
            + b"|" + _vocab_key(vocab).encode() + b"|" + str(eos_id).encode()
            + b"|" + json.dumps(kw, sort_keys=True).encode()
        ).hexdigest()
        with self._lock:
            g = self._items.pop(pre, None)
            if g is not None:
                self._items[pre] = g  # LRU bump
                return g
        g = compile_regex(pattern, vocab, eos_id, **kw)
        return self._put(pre, g)

    def _put(self, pre: str, g: TokenGrammar) -> TokenGrammar:
        with self._lock:
            self._items[pre] = g
            while len(self._items) > self.cap:
                self._items.pop(next(iter(self._items)))
        return g

    def clear(self) -> None:
        with self._lock:
            self._items.clear()


grammar_cache = _GrammarCache()
