"""Normalization ops.

RMSNorm in the Gemma convention: the learned scale is stored zero-centered
and applied as (1 + scale), and the variance is computed in float32 even for
bfloat16 activations (numerics matter more than the cast cost; XLA fuses the
whole thing into neighbouring ops anyway, so a Pallas kernel buys nothing
here — the win is in attention).
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """y = x / rms(x) * (1 + scale), computed in f32, cast back to x.dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    out = normed * (1.0 + scale.astype(jnp.float32))
    return out.astype(dtype)
