"""gofr_tpu.ops — TPU-first neural net ops.

The compute path of the framework's model-serving datasource. Everything here
is functional, jit-safe, static-shape. Hot ops (attention) have a Pallas TPU
kernel with an XLA reference fallback selected at trace time by platform.

The reference (maohieng/gofr) has no compute ops at all (SURVEY.md §2.9) —
this package exists for the TPU north star (BASELINE.json).
"""

from .attention import (
    chunk_decode_attention,
    chunk_prefill_attention,
    decode_attention,
    flash_attention,
    mha_reference,
    multi_head_attention,
    paged_chunk_decode_attention,
    paged_gather,
    paged_kernel_ok,
    ring_positions,
)
from .norms import rms_norm
from .rope import apply_rope, rope_frequencies

__all__ = [
    "multi_head_attention",
    "mha_reference",
    "flash_attention",
    "decode_attention",
    "chunk_decode_attention",
    "chunk_prefill_attention",
    "paged_chunk_decode_attention",
    "paged_gather",
    "paged_kernel_ok",
    "ring_positions",
    "rms_norm",
    "apply_rope",
    "rope_frequencies",
]
